# One-command CI for the whole framework (SURVEY.md §5 sanitizers row).
#
#   make ci          - sanitized C++ store tests, full pytest, multichip dryrun
#   make test        - pytest only
#   make native-asan - build the metadata store with ASan+UBSan
#   make dryrun      - 8-virtual-device sharded-training compile+execute check

PY ?= python
ASAN_FLAGS = -O1 -g -std=c++17 -Wall -Wextra -pthread \
             -fsanitize=address,undefined -fno-omit-frame-pointer

.PHONY: ci test test-kube kube-bench test-warmpool test-compile-depot test-serving-sched test-spec-decode test-fleet test-elastic test-obs test-pipeline test-pipeline-elastic test-quant test-disagg test-swarm native native-asan test-native-asan dryrun scale-proof clean

ci: test-native-asan test test-kube test-warmpool test-compile-depot test-serving-sched test-spec-decode test-fleet test-elastic test-obs test-pipeline test-pipeline-elastic test-quant test-disagg test-swarm dryrun
	@echo "CI OK"

# ONE kube-backend latency bench run (cold / warm-claim / warm-resubmit,
# ~2 min) feeding BOTH the warm-pool and the compile-depot assertions:
# phony, so each standalone target still produces a fresh JSON, but a
# single `make ci` invocation runs the bench once. No pipe — a pipe
# would swallow bench.py's own nonzero exit (no real claim / no real
# depot publish / resubmit missing the compile split).
KUBE_BENCH_JSON := /tmp/kft-kube-bench.json
kube-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --cluster kube > $(KUBE_BENCH_JSON)

test:
	$(PY) -m pytest tests/ -x -q

# the controller/gang suites again, UNCHANGED, over KubeCluster + the fake
# apiserver (SURVEY.md §4.2 envtest role): proves the reconciler drives the
# Kubernetes REST API, not just in-memory fakes
test-kube:
	KFT_TEST_CLUSTER=kube $(PY) -m pytest \
		tests/test_controller.py tests/test_gang.py \
		tests/test_kube_cluster.py -x -q

# kube-backend warm-pool e2e (fits the tier-1 timeout budget): the race/
# claim suite, then the shared kube bench — asserting the warm_pool
# claim/fallback counters are IN the bench JSON so a silently-dead pool
# regresses visibly. Two independent teeth: bench exits nonzero unless a
# REAL warm claim happened, then the JSON contract is checked from the
# captured file.
test-warmpool: kube-bench
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_warmpool.py -x -q
	$(PY) -c "import json; \
		d = json.loads(open('$(KUBE_BENCH_JSON)').read().strip().splitlines()[-1]); \
		wp = d['extra']['warm_pool']; \
		assert wp['claims'] >= 1, ('no warm claim happened', d); \
		assert wp['fallbacks'] >= 1, ('cold fallback not counted', d); \
		assert d['extra']['warm_claim']['phases']['imports'] < 1.0, d; \
		print('warm-pool bench OK:', json.dumps(wp))"

# executable-depot e2e (compile-once-per-gang): the unit suite, then the
# shared kube bench JSON — asserting the submit→first-step phases carry
# the compile split for ALL THREE runs (cold / warm-claim /
# warm-resubmit) and the depot publish + worker-hit + claim-prefetch
# counters are IN the bench JSON. bench.py itself exits nonzero unless a
# real claim, a real publish, and a resubmit with the split all happened
# — two independent teeth, like test-warmpool.
test-compile-depot: kube-bench
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_depot.py -x -q
	$(PY) -c "import json; \
		d = json.loads(open('$(KUBE_BENCH_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; \
		assert 'compile' in e['cold']['phases'], d; \
		assert 'compile' in e['warm_claim']['phases'], d; \
		assert 'compile' in e['warm_resubmit']['phases'], d; \
		assert e['depot'].get('kft_depot_publishes_total', 0) >= 1, d; \
		assert e['depot'].get('kft_depot_worker_hits_total', 0) >= 1, d; \
		assert e['warm_pool'].get('prefetched_entries', 0) >= 1, d; \
		print('compile-depot bench OK: depot=' + json.dumps(e['depot']) \
			+ ' compile_ratio=' + str(e.get('depot_compile_ratio')))"

# serving-scheduler e2e: the scheduler + radix-cache unit suites, then a
# bounded 128-stream shared-system-prompt bench smoke. Two independent
# teeth (like test-warmpool): bench.py exits nonzero unless every stream
# completed, the radix cache REALLY hit, and the scheduler counters are
# in the JSON; the JSON contract is then re-checked from the captured
# file so a silently-dead cache or counter rename regresses visibly.
SERVING_SMOKE_JSON := /tmp/kft-serving-smoke.json
test-serving-sched:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scheduler.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --serving-smoke > $(SERVING_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(SERVING_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; s = e['sched']; \
		assert e['prefix_hit_blocks'] > 0, ('no prefix hits', d); \
		assert e['completed'] == e['streams'] == 128, d; \
		assert e['e2e_vs_device_only'] is not None, d; \
		assert s['decode_dispatches_total'] > 0, d; \
		assert all(k in s for k in ('occupancy_ratio', 'queue_depth', \
			'preempts_total', 'prefix_hit_rate', 'admission_stalls_total')), d; \
		print('serving-sched bench OK: rps=' + str(e['requests_per_sec']) \
			+ ' prefix_hit_rate=' + str(e['prefix_hit_rate']) \
			+ ' e2e_vs_device_only=' + str(e['e2e_vs_device_only']))"

# speculative decoding + sharded-kernel e2e (ISSUE 11): the drafter/
# token-identity suite and the sharded Pallas-vs-gather parity suite,
# then a bounded spec-vs-baseline bench smoke. Two independent teeth
# (like test-serving-sched): bench.py exits nonzero unless greedy output
# was TOKEN-IDENTICAL to the non-speculative path and
# accepted_tokens_per_step held its >= 1.0 floor; the JSON contract is
# then re-checked from the captured file so a silently-vanished counter
# or ratio regresses visibly.
SPEC_SMOKE_JSON := /tmp/kft-spec-smoke.json
test-spec-decode:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_spec_decode.py \
		tests/test_paged_attention_kernel.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --spec-smoke > $(SPEC_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(SPEC_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; s = e['spec']['sched']; \
		assert e['token_identical'] is True, ('spec decode diverged', d); \
		assert e['accepted_tokens_per_step'] >= 1.0, d; \
		assert 'spec_decode_speedup' in e and 'device_step_speedup' in e, d; \
		assert s['spec_dispatches_total'] > 0, d; \
		assert s['spec_committed_tokens_total'] >= s['spec_slot_rounds_total'], d; \
		print('spec-decode bench OK: accepted/step=' \
			+ str(e['accepted_tokens_per_step']) \
			+ ' device_step_speedup=' + str(e['device_step_speedup']) \
			+ ' e2e_speedup=' + str(e['spec_decode_speedup']))"

# multi-replica serving fleet e2e (ISSUE 12): the fleet unit suite
# (ring stability, bounded-load spill, sticky canary split, autoscaler
# hysteresis, serving-vs-train claim race, canary rollback), then the
# fleet bench smoke. Two independent teeth (like test-serving-sched):
# bench.py exits nonzero unless >=2 replicas really served traffic, a
# REAL warm-claim scale-up occurred, and the JSON carries per-replica
# hit-rate + scale-latency fields; the JSON contract is then re-checked
# from the captured file so a silently-vanished counter regresses
# visibly.
FLEET_SMOKE_JSON := /tmp/kft-fleet-smoke.json
test-fleet:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --fleet-smoke > $(FLEET_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(FLEET_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; k = e['kube_fleet']; s = k['scale_up']; \
		assert k['warm_pool']['claims'] >= 1, ('no warm claim', d); \
		served = [p for p in k['replicas_2_affine']['per_replica'].values() \
			if p.get('generated_tokens', 0) > 0]; \
		assert len(served) >= 2, ('fewer than 2 replicas served', d); \
		assert all('prefix_hit_rate' in p for p in \
			k['replicas_2_affine']['per_replica'].values()), d; \
		assert s['total_replica_add_seconds'] is not None, d; \
		assert s['model_load_seconds'] is not None, d; \
		assert s['precompile_seconds'] is not None, d; \
		assert s['depot_outcome'] is not None, d; \
		r = e['affinity_sweep']['hit_rate_vs_baseline_2_replicas']; \
		assert r['affine'] >= 0.85, ('affine hit rate diluted', r); \
		assert k['canary']['decision'] == 'promote', d; \
		print('fleet bench OK: scale_up=' + json.dumps(s['depot_outcome']) \
			+ ' add_s=' + str(s['total_replica_add_seconds']) \
			+ ' affine_vs_baseline=' + str(r['affine']) \
			+ ' random_diluted=' + str(r['random_diluted']))"

# elastic preemption-tolerant training e2e (ISSUE 13): the elasticity +
# chaos suites (incl. the slow-marked real-process recovery e2es the
# tier-1 time-bounded run skips), then the recovery bench smoke. Two
# independent teeth (like test-warmpool): bench.py exits nonzero unless
# a REAL kill→warm-claim→resume cycle completed — a per-worker
# replacement with ZERO gang restarts, depot_outcome=hit with a warm
# claim and no cold fallback, the full recovery_seconds phase
# decomposition (detect/claim/load/rendezvous/first_step_after), and
# post-resume losses EXACTLY matching the uninterrupted baseline; the
# JSON contract is then re-checked from the captured file so a silently
# vanished phase or counter regresses visibly. (On rigs where
# cross-process CPU collectives are unsupported, the pre-existing
# 2-worker chaos e2e fails for that env reason — same as `make test`.)
RECOVERY_SMOKE_JSON := /tmp/kft-recovery-smoke.json
test-elastic:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_elastic.py \
		tests/test_chaos.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --recovery-smoke > $(RECOVERY_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(RECOVERY_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; p = e['phases']; c = e['loss_continuity']; \
		assert e['worker_replacements'] >= 1, ('no replacement', d); \
		assert e['gang_restarts'] == 0, ('fell back to gang restart', d); \
		assert e['depot_outcome'] == 'hit', ('cold compile on replacement', d); \
		assert e['replacement_warm_claims'] >= 1, ('no warm claim', d); \
		assert e['replacement_cold_fallbacks'] == 0, ('cold fallback', d); \
		assert all(k in p for k in ('detect', 'claim', 'load', 'rendezvous', 'first_step_after')), d; \
		assert c['exact'] is True and c['steps_compared'] >= 1, ('loss diverged', d); \
		t = e['trace']; \
		assert t['coherent'] is True and t['agrees_within_10pct'] is True, \
			('operator job trace disagrees with measured phases', t); \
		print('elastic recovery bench OK: recovery_seconds=' + str(d['value']) \
			+ ' phases=' + json.dumps(p) \
			+ ' resumed_from=' + str(e['resumed_from_step']))"

# end-to-end observability (ISSUE 14): the obs unit suite (span
# collector ring/races, histogram percentiles, exposition lint against
# BOTH /metrics surfaces, trace propagation under failure, profiler env
# wiring), then the obs bench smoke. Two independent teeth (like
# test-serving-sched): bench.py exits nonzero unless ONE real served
# request produced a >=6-span trace (router/server/queue/prefill-chunk/
# decode-step sharing a propagated trace id), the Perfetto export
# loads, /metrics lints clean and all three request histograms have
# nonzero counts; the JSON contract is then re-checked from the
# captured file so a silently-vanished span family or histogram
# regresses visibly.
OBS_SMOKE_JSON := /tmp/kft-obs-smoke.json
test-obs:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_obs.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --obs-smoke > $(OBS_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(OBS_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; names = set(e['span_names']); \
		assert e['trace_spans'] >= 6, ('trace too shallow', d); \
		assert {'router.route', 'server.infer', 'request.queue', \
			'prefill.chunk', 'decode.step'} <= names, names; \
		assert e['trace_coherent'] is True, ('orphan spans', d); \
		assert all(e['histogram_counts'][k] > 0 for k in ('ttft', 'itl', 'e2e')), d; \
		assert e['metrics_valid'] is True, ('exposition lint failed', e.get('metrics_lint')); \
		assert e['perfetto_events'] >= 6, d; \
		print('obs bench OK: spans=' + str(e['trace_spans']) \
			+ ' hist_counts=' + json.dumps(e['histogram_counts']) \
			+ ' export=' + str(e['perfetto_export']))"

# MPMD pipeline parallelism e2e (ISSUE 15 + interleaved ISSUE 19): the
# mpmd unit + parity suites (schedule math, transport, GPipe==1F1B
# bitwise identity, SPMD pipeline_apply oracle parity, stage rendezvous
# + per-worker replacement, per-stage depot keys, interleaved tick-plan
# validity / stash bounds / per-chunk depot keys / llama-vs-oracle
# parity), then the pipeline bench smoke. Two independent teeth (like
# test-warmpool): bench.py exits nonzero unless a REAL multi-process
# >=2-stage 1F1B run completed with its loss trajectory matching the
# SPMD oracle, measured GPipe bubble within 35% of the analytic
# (S-1)/(S+M-1) fill-drain bound (wide: machine load shifts absolute
# timings; the ORDERING gates below are load-invariant and strict),
# 1F1B (at GPipe's activation budget) STRICTLY below both, the REAL
# transformer (pipeline_llama) through the runner with the interleaved
# V=2 leg measuring STRICTLY below both the plain-1F1B llama
# measurement and the single-stage analytic floor at matched M,
# activation stash within the V-chunk accounting bound, warm-vs-cold
# interleaved loss bitwise, llama-vs-SPMD-oracle step-0 bitwise +
# <=2e-5 trajectory, per-chunk depot hits on the warm leg, per-chunk
# trace lanes, the v5p-128 bubble re-projection present,
# dcn_overlap_fraction reported, and pipeline.tick/dcn.transfer spans
# in the operator job trace; the JSON contract is then re-checked from
# the captured file so a silently vanished field regresses visibly.
PIPELINE_SMOKE_JSON := /tmp/kft-pipeline-smoke.json
test-pipeline:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_mpmd.py \
		tests/test_mpmd_interleaved.py tests/test_depot.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --pipeline-smoke > $(PIPELINE_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(PIPELINE_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; s = e['summary']; p = e['parity']; lp = e['llama_parity']; \
		assert p['schedules_bitwise_identical'] is True, ('gpipe != 1f1b', p); \
		assert p['oracle_step0_bitwise'] is True and p['oracle_max_rel_diff'] <= 2e-5, p; \
		b = s['gpipe_bubble_measured']; a = s['gpipe_bubble_analytic']; \
		assert abs(b - a) / a <= 0.35, ('gpipe bubble vs analytic', b, a); \
		f = s['one_f1b_2m_bubble_measured']; \
		assert f < b and f < a, ('1f1b did not beat gpipe', f, b, a); \
		assert s['dcn_overlap_fraction'] is not None, s; \
		assert e['one_f1b']['depot_outcome'] == 'hit', ('stage depot miss', e['one_f1b']['depot']); \
		assert e['trace']['has_pipeline_ticks'] and e['trace']['has_dcn_transfers'], e['trace']; \
		li = s['llama_interleaved_bubble_measured']; \
		lpm = s['llama_1f1b_bubble_measured']; \
		lf = s['llama_plain_floor_analytic']; \
		assert li < lpm and li < lf, ('interleaved did not beat plain+floor', li, lpm, lf); \
		assert all(x <= y for x, y in zip(s['llama_interleaved_stash'], s['llama_interleaved_stash_bound'])), s; \
		assert lp['warm_bitwise_identical'] is True, lp; \
		assert lp['oracle_step0_bitwise'] is True and lp['oracle_max_rel_diff'] <= 2e-5, lp; \
		assert lp['plain_max_rel_diff'] <= 2e-5, lp; \
		assert e['trace']['has_chunk_lanes'] is True, e['trace']; \
		assert s['v5p128_bubble_projected'] is not None, s; \
		assert 'measured' in s['est_basis'], s; \
		print('pipeline bench OK: gpipe_bubble=' + str(b) + ' (analytic ' + str(a) + ')' \
			+ ' 1f1b_2m=' + str(f) \
			+ ' llama_inter=' + str(li) + ' < 1f1b=' + str(lpm) + ' < floor=' + str(lf) \
			+ ' v5p128_proj=' + str(s['v5p128_bubble_projected']) \
			+ ' overlap=' + str(s['dcn_overlap_fraction']) \
			+ ' oracle_drift=' + str(lp['oracle_max_rel_diff']))"

# elastic MPMD pipeline e2e (ISSUE 20): the elastic suites (snapshot
# store prune/common-step, epoch fencing at TCP ingress, rollback-and-
# replay bitwise parity, mailbox poison with cause, close() frees the
# stage port for in-process rebind, double-failure and budget-exhaustion
# reconciler model tests, counter exposition lint) plus the wrap-link
# poison regressions, then the chaos bench smoke. Two independent teeth
# (like test-pipeline): bench.py exits nonzero unless a stage worker
# SIGKILLed mid-run was REPLACED (not gang-restarted) via the warm pool
# with depot hits, survivors reformed in process at the bumped epoch,
# the post-recovery loss trajectory is bitwise-equal to an unkilled
# control leg, the replayed-microbatch count equals its accounting
# bound, and the stale-frame fence counted at least one dropped frame;
# the JSON contract is then re-checked from the captured file so a
# silently vanished recovery field regresses visibly.
PIPELINE_ELASTIC_SMOKE_JSON := /tmp/kft-pipeline-elastic-smoke.json
test-pipeline-elastic:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_mpmd_elastic.py -x -q
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_mpmd_interleaved.py \
		-x -q -k "wrap_next_peer or wrap_prev_peer"
	JAX_PLATFORMS=cpu $(PY) bench.py --pipeline-chaos-smoke > $(PIPELINE_ELASTIC_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(PIPELINE_ELASTIC_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; r = e['replacement']; p = e['parity']; rec = e['pipeline.recovery']; \
		assert r['worker_replacements'] >= 1 and r['gang_restarts'] == 0, r; \
		assert r['zygote_fallbacks_during_recovery'] == 0, ('cold fork', r); \
		assert r['depot_outcome'] == 'hit', ('replacement depot miss', r); \
		assert p['full_length'] is True and p['bitwise_equal'] is True, ('replay not bitwise', p); \
		assert rec['replayed_microbatches'] == rec['replay_bound'], rec; \
		assert rec['stale_frames_fenced'] > 0 and rec['rendezvous_epoch'] >= 1, rec; \
		ph = rec['phases']; \
		assert all(k in ph for k in ('detect', 'claim', 're_rendezvous', 'restore', 'compile', 'replay_window', 'first_tick_after')), ph; \
		print('pipeline elastic bench OK: recovery=' + str(round(rec['recovery_seconds'], 3)) + 's' \
			+ ' restored_step=' + str(rec['restored_step']) \
			+ ' replayed_mb=' + str(rec['replayed_microbatches']) \
			+ ' fenced=' + str(rec['stale_frames_fenced']) \
			+ ' epoch=' + str(rec['rendezvous_epoch']))"

# quantized serving e2e (ISSUE 16): the quant suites (quantized-kernel
# vs quantized-gather-oracle exactness incl. sharded tensor=2, write-path
# scale growth, exact-parity proven bitwise, spec x quant token identity,
# counted downgrades, per-config depot keys, KFT_QUANT_* env roundtrip)
# plus the kernel parity suite unchanged, then the quant bench smoke.
# Two independent teeth (like test-serving-sched): bench.py exits
# nonzero unless int8-KV served real decode steps, teacher-forced greedy
# agreement + max logit drift landed within the budgets STATED in the
# same JSON, exact-parity mode proved bitwise, and the quantized
# param_read roofline fields (bytes_per_weight / bytes_per_kv_token /
# est_basis naming the quant config) are present; the JSON contract is
# then re-checked from the captured file so a silently-loosened budget
# or vanished field regresses visibly.
QUANT_SMOKE_JSON := /tmp/kft-quant-smoke.json
test-quant:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_quant.py \
		tests/test_paged_attention_kernel.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --quant-smoke > $(QUANT_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(QUANT_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; q = e['quality']; b = e['param_read']; \
		assert e['device_step_ms']['int8'] is not None, ('int8 never served', d); \
		assert q['within_budget'] is True, ('quality outside budget', q); \
		assert q['greedy_token_agreement'] >= q['greedy_agreement_budget'], q; \
		assert q['max_logit_drift'] <= q['max_logit_drift_budget'], q; \
		assert e['exact_parity_bitwise'] is True, ('parity hatch not bitwise', d); \
		assert b['bytes_per_weight']['quantized'] < b['bytes_per_weight']['baseline'], b; \
		assert b['bytes_per_kv_token']['quantized'] < b['bytes_per_kv_token']['baseline'], b; \
		assert 'int8' in b['est_basis'], b; \
		print('quant bench OK: agreement=' + str(q['greedy_token_agreement']) \
			+ ' drift=' + str(q['max_logit_drift']) \
			+ ' bytes/weight=' + str(b['bytes_per_weight']['quantized']) \
			+ ' bytes/kv_token=' + str(b['bytes_per_kv_token']['quantized']))"

# disaggregated prefill/decode serving e2e (ISSUE 17): the disagg unit
# suite (engine hold/export/inject hooks, TCP handoff races — abort,
# duplicate delivery, eviction pinning, decode-pod death fallback —
# tier-aware controller/autoscaler, spill-saturation trigger, tier
# labels on /metrics, TieredRouter bypass), then the disagg bench
# smoke. Two independent teeth (like test-fleet): bench.py exits
# nonzero unless a REAL cross-pod KV migration moved blocks between
# real tier processes, BOTH tier scale-up replicas depot-hit their
# stage-scoped programs, the migration decomposition landed, and the
# radix-bypass leg skipped the prefill tier with a counted
# prefill_bypasses; the JSON contract is then re-checked from the
# captured file so a silently-vanished counter regresses visibly.
DISAGG_SMOKE_JSON := /tmp/kft-disagg-smoke.json
test-disagg:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_disagg.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --disagg-smoke > $(DISAGG_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(DISAGG_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; dis = e['disagg_1p1d']; sc = e['tier_scale_up']; \
		bp = e['bypass']; hl = e['high_load_p95']; \
		assert dis['migrated_blocks'] > 0, ('no real migration', d); \
		assert dis['statuses'].get('migrated', 0) > 0, d; \
		assert dis['decode_tier']['handoffs_injected_total'] > 0, d; \
		mdc = dis['migration_decomposition']; \
		assert mdc['prefill_done_to_first_commit_s'] is not None, d; \
		assert mdc['export_s'] is not None and mdc['transfer_s'] is not None, d; \
		assert sc['prefill']['depot_outcome'] == 'hit', ('prefill tier depot miss', sc); \
		assert sc['decode']['depot_outcome'] == 'hit', ('decode tier depot miss', sc); \
		assert bp['plan_warm_prompt']['bypass'] is True, ('bypass never fired', bp); \
		assert bp['router']['prefill_bypasses'] >= 1, bp; \
		assert hl['ttft_disagg_s'] is not None and hl['itl_disagg_s'] is not None, d; \
		print('disagg bench OK: migrated_blocks=' + str(dis['migrated_blocks']) \
			+ ' handoff_p95=' + str(mdc['prefill_done_to_first_commit_s'].get('p95_s')) \
			+ ' ttft_p95 co=' + str(hl['ttft_colocated_s']) + ' dsg=' + str(hl['ttft_disagg_s']) \
			+ ' itl_p95 co=' + str(hl['itl_colocated_s']) + ' dsg=' + str(hl['itl_disagg_s']))"

# Podracer trial swarm e2e (ISSUE 18 + suggestion batching ISSUE 19):
# the swarm unit suite (shared-compile fingerprint keying,
# one-publish-then-hits through a real depot, reclaim races — kill vs
# completion exactly one terminal state, token fence against a stale
# trial's late exec, dead/gone pod counted no-op, concurrent
# convergence — suggestion determinism across controller restart,
# operator metric surface) plus the suggestion-batching suite (one
# batched draw per reconcile pass, buffered-tail re-derivation on
# restart), then the swarm bench smoke. Two independent teeth (like
# test-elastic): bench.py exits nonzero unless trials REALLY claimed
# warm zygote pods, the shared-compile invariant held (depot publishes
# == distinct structural configs, every other recorded trial a hit,
# zero local compiles), at least one early-stopped trial's pod
# completed a reclaim→re-claim cycle, the whole sweep cost exactly ONE
# suggestion-service call (max 1 per pass — ROADMAP 4c amortization),
# and trials_per_hour was measured; the JSON contract is then
# re-checked from the captured file so a silently-vanished counter or
# a collapsed warm path regresses visibly.
SWARM_SMOKE_JSON := /tmp/kft-swarm-smoke.json
test-swarm:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_swarm.py \
		tests/test_hpo_batching.py -x -q
	JAX_PLATFORMS=cpu $(PY) bench.py --swarm-smoke > $(SWARM_SMOKE_JSON)
	$(PY) -c "import json; \
		d = json.loads(open('$(SWARM_SMOKE_JSON)').read().strip().splitlines()[-1]); \
		e = d['extra']; s = e['swarm']; sc = e['shared_compile']; \
		dec = e['submit_to_first_step']; \
		assert s['warm_claims'] >= 1, ('no warm claim', d); \
		assert sc['holds'] is True, ('shared-compile invariant broken', sc); \
		assert sc['published'] == sc['distinct_structural_configs'], sc; \
		assert sc['local_compiles'] == 0, ('a trial compiled locally', sc); \
		assert e['counts'].get('EarlyStopped', 0) >= 1, ('nothing early-stopped', d); \
		assert s['reclaims'] >= 1, ('no pod reclaimed', d); \
		assert e['reclaim_cycles'] >= 1, ('no reclaim→re-claim cycle', d); \
		assert dec['warm']['trials'] >= 1 and dec['warm']['total'] is not None, dec; \
		assert e['trials_per_hour'] is not None, d; \
		assert e['metrics_exposition']['clean'] is True, e['metrics_exposition']; \
		assert e['trace']['coherent'] is True, e['trace']; \
		sg = e['suggestions']; \
		assert sg['calls_total'] == 1 and sg['max_calls_per_pass'] == 1, ('suggestion draws not batched', sg); \
		print('swarm bench OK: trials_per_hour=' + str(e['trials_per_hour']) \
			+ ' warm=' + str(s['warm_claims']) + '/' + str(s['trials_running']) \
			+ ' publishes=' + str(sc['published']) + ' hits=' + str(sc['hits']) \
			+ ' suggestion_calls=' + str(sg['calls_total']) + ' (x' + str(sg['trials_per_call']) + ')' \
			+ ' reclaim_cycles=' + str(e['reclaim_cycles']))"

native:
	$(MAKE) -C native/metadata_store

native-asan:
	$(MAKE) -C native/metadata_store clean
	$(MAKE) -C native/metadata_store CXXFLAGS="$(ASAN_FLAGS)"

# run the metadata tests against the sanitized binary, then drop it so later
# builds rebuild the optimized one (build_native() rebuilds on mtime)
test-native-asan: native-asan
	$(PY) -m pytest tests/test_metadata.py -x -q
	$(MAKE) -C native/metadata_store clean

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
		$(PY) __graft_entry__.py dryrun 8

# AOT scale proofs (BASELINE.md rows 4-5): compile 8B serving for a v5p-8
# slice and the 70B FSDP train step for a 2-slice v5p-128 with the REAL
# XLA:TPU compiler (compile-only topology, no TPU attached); fails if the
# per-chip HBM requirement exceeds the 95G budget
scale-proof:
	JAX_PLATFORMS=cpu $(PY) -m kubeflow_tpu.parallel.aot

clean:
	$(MAKE) -C native/metadata_store clean
