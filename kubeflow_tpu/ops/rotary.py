"""Rotary position embeddings (RoPE), including Llama-3 frequency scaling."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 500000.0,
    scaling: str | None = "llama3",
    scale_factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_seq: int = 8192,
) -> np.ndarray:
    """Inverse frequencies [head_dim//2], optionally Llama-3-scaled for long context."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if scaling == "llama3":
        low_wavelen = original_max_seq / low_freq_factor
        high_wavelen = original_max_seq / high_freq_factor
        wavelen = 2 * np.pi / inv
        # three bands: keep high-freq, scale low-freq, smooth in between
        smooth = (original_max_seq / wavelen - low_freq_factor) / (
            high_freq_factor - low_freq_factor
        )
        scaled = np.where(
            wavelen > low_wavelen,
            inv / scale_factor,
            np.where(
                wavelen < high_wavelen,
                inv,
                (1 - smooth) * inv / scale_factor + smooth * inv,
            ),
        )
        inv = scaled
    return inv.astype(np.float32)


def apply_rope(x, positions, inv_freq):
    """Apply rotary embedding.

    x: [..., seq, heads, head_dim]; positions: [..., seq] int32;
    inv_freq: [head_dim//2].
    """
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., S, D/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
