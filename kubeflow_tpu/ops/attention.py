"""Causal grouped-query attention for TPU.

Two execution paths, selected by `impl`:

- "xla": plain einsum attention. XLA fuses softmax chains well on TPU and this
  is the correct baseline + CPU-test path.
- "flash": Pallas TPU flash-attention kernel (blockwise, O(S) memory). Uses
  the stock `jax.experimental.pallas.ops.tpu.flash_attention` kernel; a
  first-party splash-style kernel lives in ops/pallas_attention.py and can be
  selected with "pallas".

All paths take q:[B,S,H,D] k/v:[B,S,KV,D] and return [B,S,H,D]. GQA is
handled by repeating KV heads logically (einsum grouping), never materialized.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, *, causal: bool, q_offset=0, bias=None):
    b, sq, h, d = q.shape
    _, skv, kvh, _ = k.shape
    groups = h // kvh
    qf = q.astype(jnp.float32).reshape(b, sq, kvh, groups, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qf * scale, kf)
    if bias is not None:
        logits = logits + bias
    if causal:
        # q_offset may be a scalar (all rows share one offset — prefill /
        # chunked prefill) or a [B] array (per-slot offsets — the batched
        # speculative-decode verify step); either broadcasts to [B?, Sq]
        q_pos = jnp.arange(sq)[None, :] + jnp.atleast_1d(
            jnp.asarray(q_offset))[:, None]
        kv_pos = jnp.arange(skv)
        mask = q_pos[:, :, None] >= kv_pos[None, None, :]   # [B|1, Sq, Skv]
        logits = jnp.where(mask[:, None, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, vf)
    return out.reshape(b, sq, h, d).astype(q.dtype)


def attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    impl: str = "xla",
    q_offset: int | jax.Array = 0,
    block_q: int = 512,
    block_kv: int = 512,
):
    """Multi-head / grouped-query attention.

    q: [B, Sq, H, D]; k, v: [B, Skv, KV_H, D] with H % KV_H == 0.
    `q_offset` shifts query positions for causal masking during decode.

    Validation happens out here, unjitted: under jit an explicitly-passed
    q_offset=0 would trace to a Tracer and defeat the isinstance check.
    """
    if impl in ("flash", "pallas") and not (
            isinstance(q_offset, int) and q_offset == 0):
        raise ValueError(
            f"impl={impl!r} does not support q_offset; use impl='xla' "
            "(decode paths use decode_attention)")
    return _attention_jit(q, k, v, causal=causal, impl=impl,
                          q_offset=q_offset, block_q=block_q,
                          block_kv=block_kv)


@functools.partial(
    jax.jit, static_argnames=("causal", "impl", "block_q", "block_kv")
)
def _attention_jit(
    q,
    k,
    v,
    *,
    causal: bool,
    impl: str,
    q_offset,
    block_q: int,
    block_kv: int,
):
    platform = jax.default_backend()
    if impl == "flash":
        if platform != "tpu":
            # the stock kernel has no interpreter path; xla is the
            # numerics-identical CPU/GPU stand-in
            return _xla_attention(q, k, v, causal=causal)
        return _flash_attention(q, k, v, causal=causal, block_q=block_q, block_kv=block_kv)
    if impl == "pallas":
        if platform not in ("tpu", "cpu"):
            return _xla_attention(q, k, v, causal=causal)
        from kubeflow_tpu.ops.pallas_attention import flash_attention as own_flash

        kernel = functools.partial(
            own_flash, causal=causal, block_q=block_q,
            block_kv=block_kv, interpret=platform == "cpu")
        return _shard_mapped(kernel, q, k, v)
    return _xla_attention(q, k, v, causal=causal, q_offset=q_offset)


def _ambient_mesh():
    """The mesh in context at trace time: `with mesh:` populates the
    thread-resource env (what with_sharding_constraint resolves against);
    newer `jax.sharding.use_mesh` populates the abstract mesh instead —
    accept either. Version-tolerant: ``jax.sharding.get_abstract_mesh``
    only exists on newer jax (0.5+); older eras (0.4.x) have no abstract
    mesh at all, so the thread-resource fallback below is the whole
    story there."""
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        abstract = get_abstract()
        if abstract is not None and abstract.axis_names:
            return abstract
    try:
        from jax._src.mesh import thread_resources

        physical = thread_resources.env.physical_mesh
        if physical.axis_names:
            return physical
    except Exception:
        pass
    return None


def _shard_mapped(kernel, q, k, v):
    """Partition a Mosaic kernel over the ambient mesh.

    XLA auto-partitions plain HLO, but Mosaic (Pallas) calls must be
    wrapped in shard_map. Per the model's logical rules the flash kernel
    parallelizes over batch (data/fsdp axes) and heads (tensor); sequence
    stays local — context parallelism is ring/Ulysses attention's job
    (parallel/ring_attention.py), never this kernel's."""
    mesh = _ambient_mesh()
    if mesh is None or not mesh.axis_names:
        return kernel(q, k, v)
    have = set(mesh.axis_names)
    batch_axes = tuple(a for a in ("data", "fsdp")
                       if a in have and mesh.shape[a] > 1)
    head_axis = "tensor" if "tensor" in have and mesh.shape["tensor"] > 1 \
        else None
    if not batch_axes and head_axis is None:
        return kernel(q, k, v)
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axes or None, None, head_axis, None)
    try:
        # check_vma=False: pallas_call's out_shape ShapeDtypeStructs carry
        # no varying-mesh-axes annotation, which strict vma checking rejects
        wrapped = jax.shard_map(
            kernel, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)
    except (TypeError, AttributeError):   # older jax: no check_vma / no jax.shard_map
        # jax 0.4.x spells the same escape hatch check_rep=False (pallas
        # has no replication rule on that era either)
        from jax.experimental.shard_map import shard_map as _old_shard_map

        wrapped = _old_shard_map(kernel, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_rep=False)
    return wrapped(q, k, v)


def _flash_attention(q, k, v, *, causal, block_q, block_kv):
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    b, s, h, d = q.shape
    kvh = k.shape[2]
    if h != kvh:
        # stock kernel wants matching head counts; expand KV (still O(S) mem)
        k = jnp.repeat(k, h // kvh, axis=2)
        v = jnp.repeat(v, h // kvh, axis=2)
    # kernel layout is [B, H, S, D]
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    sizes = fa.BlockSizes(
        block_q=min(block_q, s),
        block_k_major=min(block_kv, s),
        block_k=min(block_kv, s),
        block_b=1,
        block_q_major_dkv=min(block_q, s),
        block_k_major_dkv=min(block_kv, s),
        block_k_dkv=min(block_kv, s),
        block_q_dkv=min(block_q, s),
        block_k_major_dq=min(block_kv, s),
        block_k_dq=min(block_kv, s),
        block_q_dq=min(block_q, s),
    )
    out = fa.flash_attention(
        qt, kt, vt, causal=causal,
        sm_scale=1.0 / (d ** 0.5),
        block_sizes=sizes,
    )
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def decode_attention(q, k_cache, v_cache, cache_len):
    """Single-step decode attention against a KV cache.

    q: [B, 1, H, D]; k_cache/v_cache: [B, S_max, KV, D]; cache_len: [B] int32
    (number of valid cache entries per sequence, including this step).
    """
    b, _, h, d = q.shape
    kvh = k_cache.shape[2]
    groups = h // kvh
    qf = q.astype(jnp.float32).reshape(b, kvh, groups, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    logits = jnp.einsum("bkgd,bskd->bkgs", qf * scale, k_cache.astype(jnp.float32))
    mask = jnp.arange(k_cache.shape[1])[None, :] < cache_len[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(b, 1, h, d).astype(q.dtype)
