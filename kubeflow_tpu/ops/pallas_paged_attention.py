"""First-party Pallas TPU paged-attention DECODE kernel.

The serving bottleneck this kernel removes (VERDICT r5 "What's weak" #4):
the gather decode path materializes each slot's full logical ``[max_seq]``
KV view every layer (``k_pool[tables]``), so per-step HBM traffic scales
with the ARENA, not with the tokens actually live — measured 7.44 ms/step
against a 2.01 ms param-read bandwidth bound at batch 32. The stock
``jax.experimental.pallas.ops.tpu.paged_attention`` kernel does not lower
at our proxy shapes (small query groups / non-256 head_dim), so this is
the first-party replacement, the same way ``ops/pallas_attention.py`` is
the first-party training flash kernel.

Design (decode only — one query token per slot):

- Grid ``(batch, max_blocks_per_seq)``; the block-table row and live
  lengths ride in as **scalar-prefetch** operands, so the K/V BlockSpec
  index maps dereference ``tables[b, j]`` — the pool block, not the
  logical position — while the pipeline prefetches.
- Iterations past a slot's live block count (``ceil(kv_len/block)``, NOT
  ``max_blocks_per_seq``) are pinned by the index map to the slot's LAST
  live block: Pallas elides the re-fetch of an unchanged block, so dead
  tail iterations issue **no DMA and no compute** (`pl.when`-guarded) —
  per-step HBM traffic is O(live tokens), the paged-attention property.
- GQA in-kernel: query heads are grouped over KV heads (``groups = H /
  KV_H``); each pool block is fetched ONCE per slot and every group's
  ``[G, D] x [D, block]`` logit tile is computed from it — KV heads are
  never repeated, and no ``[max_seq]`` view ever exists.
- Online softmax across a slot's blocks (running max / sum / weighted
  accumulator in VMEM scratch, f32), exactly the flash recurrence the
  training kernel uses.
- The K/V pools enter as ``[num_blocks, block, KV_H * D]`` (a free
  reshape of the engine pool layout): per-head slices are then LANE
  slices at multiples of D — cheap and layout-friendly — instead of
  strided sublane gathers over a ``[block, KV_H, D]`` tile.

``interpret=True`` runs the identical kernel logic on CPU (tier-1 tests);
the gather path in ``serving/paged_kv.py`` stays available as the
reference oracle behind the same ``kernel=`` switch.

Mesh partitioning: grouped-query attention is embarrassingly parallel
over KV heads — every query-head group attends ONLY its own KV head, and
the online-softmax state never crosses groups. So a tensor-sharded
engine (KV pool sharded on the kv-head dim, q sharded on heads by the
same factor) runs the kernel under ``shard_map``
(``paged_decode_attention_sharded``): each shard streams its LOCAL pool
blocks through VMEM against its local query heads, block tables and
lengths replicated, zero collectives. XLA cannot auto-partition a Mosaic
call, which is why the gather oracle used to be the only sharded path;
the shard_map wrapper removes that downgrade.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30      # same mask value as the gather path (decode_attention)


def _decode_kernel(kvlen_ref, tables_ref, q_ref, k_ref, v_ref, *rest,
                   scale, block_size, kv_heads, groups, head_dim,
                   quantized=False):
    if quantized:
        # quantized pools ride with per-block per-kv-head scale tiles
        # ([1, KV_H] f32, same index-map clipping as the pool blocks)
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)
    kv_len = kvlen_ref[b]
    n_live = pl.cdiv(kv_len, block_size)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        # kv_len >= 1 always (the decode step just wrote this step's row),
        # but an all-dead slot must still leave defined output
        o_ref[0] = jnp.zeros_like(o_ref[0])

    @pl.when(j < n_live)
    def _contribute():
        q = q_ref[0].astype(jnp.float32) * scale             # [H, D]
        # logits for every query head against this block, grouped: the
        # block is resident ONCE; each KV head's [block, D] tile is a lane
        # slice feeding its group's [G, D] x [D, block] matmul
        rows = []
        for h in range(kv_heads):
            qh = q[h * groups:(h + 1) * groups]              # [G, D]
            kh = k_ref[0, :, h * head_dim:(h + 1) * head_dim].astype(
                jnp.float32)
            if ks_ref is not None:
                # dequant fused into the online-softmax inner loop: the
                # int8/fp8 tile upcasts and multiplies its block's
                # per-kv-head scale between DMA and the MXU — the exact
                # per-element pipeline the gather oracle runs, so
                # kernel-vs-oracle parity stays bit-for-bit in f32
                kh = kh * ks_ref[0, h]
            rows.append(jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))         # [G, block]
        s = jnp.concatenate(rows, axis=0)                    # [H, block]
        kv_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kv_pos < kv_len, s, NEG_INF)

        # online-softmax recurrence; m/l scratch is lane-replicated so the
        # [H, 128] tiles stay aligned (only lane 0 is meaningful)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])                        # [H, block]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        rows = []
        for h in range(kv_heads):
            ph = p[h * groups:(h + 1) * groups]              # [G, block]
            vh = v_ref[0, :, h * head_dim:(h + 1) * head_dim].astype(
                jnp.float32)
            if vs_ref is not None:
                vh = vh * vs_ref[0, h]
            rows.append(jax.lax.dot_general(
                ph, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))         # [G, D]
        acc = acc_ref[...] * alpha[:, :1] + jnp.concatenate(rows, axis=0)
        m_ref[...], l_ref[...], acc_ref[...] = m_new, l_new, acc
        # the output block is revisited across j (its index map ignores
        # j), so writing the normalized running state every live block
        # costs VMEM traffic only; the last live write is what lands
        o_ref[0] = (acc / jnp.maximum(l_new[:, :1], 1e-30)).astype(
            o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, tables, kv_len, *,
                           interpret: bool = False,
                           k_scale=None, v_scale=None):
    """Block-resident paged GQA decode attention.

    q: [B, H, D] (this step's query rows); k_pool/v_pool:
    [num_blocks, block_size, KV_H, D] (the paged pools, current step's KV
    row already scattered in); tables: [B, max_blocks_per_seq] int32 pool
    block ids in logical order; kv_len: [B] int32 live rows per slot
    INCLUDING this step. Returns [B, H, D] in q.dtype.

    k_scale/v_scale: [num_blocks, KV_H] f32 per-block per-kv-head scales
    of an int8/fp8-quantized pool (both or neither). When given, each
    fetched pool tile dequants (upcast * scale) inside the online-
    softmax inner loop — the scale tiles ride the same scalar-prefetch
    index map as the pool blocks, so dead-tail iterations elide their
    DMA too.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    b, h, d = q.shape
    num_blocks, block_size, kvh, d_k = k_pool.shape
    if d != d_k:
        raise ValueError(f"head_dim mismatch: q has {d}, pool has {d_k}")
    if h % kvh:
        raise ValueError(f"H={h} not a multiple of KV_H={kvh}")
    groups = h // kvh
    n_tables = tables.shape[1]
    # free reshape (contiguous): per-head tiles become lane slices
    k2 = k_pool.reshape(num_blocks, block_size, kvh * d)
    v2 = v_pool.reshape(num_blocks, block_size, kvh * d)
    kv_len = kv_len.astype(jnp.int32)
    tables = tables.astype(jnp.int32)

    def kv_map(bi, j, kvlen_ref, tables_ref):
        # past the live tail, pin to the last live block: the unchanged
        # block index elides the DMA (idle slots pin to block 0, fetched
        # once)
        n_live = pl.cdiv(kvlen_ref[bi], block_size)
        jc = jnp.clip(jnp.minimum(j, n_live - 1), 0, n_tables - 1)
        return (tables_ref[bi, jc], 0, 0)

    def scale_map(bi, j, kvlen_ref, tables_ref):
        n_live = pl.cdiv(kvlen_ref[bi], block_size)
        jc = jnp.clip(jnp.minimum(j, n_live - 1), 0, n_tables - 1)
        return (tables_ref[bi, jc], 0)

    in_specs = [
        pl.BlockSpec((1, h, d), lambda bi, j, *_: (bi, 0, 0)),
        pl.BlockSpec((1, block_size, kvh * d), kv_map),
        pl.BlockSpec((1, block_size, kvh * d), kv_map),
    ]
    args = (kv_len, tables, q, k2, v2)
    if k_scale is not None:
        if k_scale.shape != (num_blocks, kvh):
            raise ValueError(f"k_scale shape {k_scale.shape} != "
                             f"{(num_blocks, kvh)}")
        in_specs += [pl.BlockSpec((1, kvh), scale_map),
                     pl.BlockSpec((1, kvh), scale_map)]
        args += (k_scale.astype(jnp.float32), v_scale.astype(jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_tables),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda bi, j, *_: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),   # running max (lane-repl.)
            pltpu.VMEM((h, 128), jnp.float32),   # running sum
            pltpu.VMEM((h, d), jnp.float32),     # running weighted values
        ],
    )
    kernel = functools.partial(
        _decode_kernel, scale=1.0 / (d ** 0.5), block_size=block_size,
        kv_heads=kvh, groups=groups, head_dim=d,
        quantized=k_scale is not None)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(*args)


def shard_unsupported_reason(mesh, n_kv_heads: int,
                             axis: str = "tensor"):
    """Why ``paged_decode_attention_sharded`` cannot run on this mesh, or
    None when it can. The one hard constraint is the engine's own pool
    constraint: the KV-head dim must split evenly over ``axis``. Mesh
    axes the specs don't mention (data/fsdp in a mixed topology) are
    fine — shard_map treats them as replication, which the serving
    engine's tensor-only pool sharding already guarantees."""
    if mesh is None:
        return None
    sizes = dict(getattr(mesh, "shape", {}) or {})
    tp = int(sizes.get(axis, 1))
    if tp > 1 and n_kv_heads % tp:
        return (f"n_kv_heads={n_kv_heads} not divisible by "
                f"{axis}={tp}")
    return None


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: Mosaic calls have no replication /
    varying-mesh-axes rule, so the check must be off (the specs here are
    correct by construction — per-KV-head groups are independent)."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (TypeError, AttributeError):
        from jax.experimental.shard_map import shard_map as _old

        return _old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=False)


def paged_decode_attention_sharded(q, k_pool, v_pool, tables, kv_len, *,
                                   mesh, axis: str = "tensor",
                                   interpret: bool = False,
                                   k_scale=None, v_scale=None):
    """``paged_decode_attention`` partitioned over the mesh's heads/KV
    axis with shard_map: q [B, H, D] shards on H, pools
    [NB, bs, KV_H, D] on KV_H, block tables and lengths replicated —
    each shard's table row names the same pool blocks, but only the
    local kv-head slice of them is resident per chip. No collectives:
    softmax state is private to each query-head group.

    Falls back to the unwrapped kernel when the mesh doesn't shard
    ``axis`` (a 1-sized axis needs no partitioning); raises for
    topologies the kernel cannot shard (see shard_unsupported_reason) —
    callers decide the gather downgrade, not this function.

    Quantized pools: the [NB, KV_H] scale tables shard on their kv-head
    dim with the pools (``P(None, axis)``) — each shard dequants its
    local kv-head slice with its local scales, still zero collectives."""
    kvh = k_pool.shape[2]
    reason = shard_unsupported_reason(mesh, kvh, axis)
    if reason is not None:
        raise ValueError(f"cannot shard paged attention: {reason}")
    if mesh is None or int(dict(mesh.shape).get(axis, 1)) <= 1:
        return paged_decode_attention(q, k_pool, v_pool, tables, kv_len,
                                      interpret=interpret,
                                      k_scale=k_scale, v_scale=v_scale)
    if k_scale is None:
        kern = functools.partial(paged_decode_attention,
                                 interpret=interpret)
        wrapped = _shard_map(
            kern, mesh,
            in_specs=(P(None, axis, None), P(None, None, axis, None),
                      P(None, None, axis, None), P(None, None), P(None)),
            out_specs=P(None, axis, None))
        return wrapped(q, k_pool, v_pool, tables, kv_len)

    def kern(qs, kp, vp, t, kl, ks, vs):
        return paged_decode_attention(qs, kp, vp, t, kl,
                                      interpret=interpret,
                                      k_scale=ks, v_scale=vs)

    wrapped = _shard_map(
        kern, mesh,
        in_specs=(P(None, axis, None), P(None, None, axis, None),
                  P(None, None, axis, None), P(None, None), P(None),
                  P(None, axis), P(None, axis)),
        out_specs=P(None, axis, None))
    return wrapped(q, k_pool, v_pool, tables, kv_len, k_scale, v_scale)
