"""Normalization ops. RMSNorm is the Llama-family workhorse.

Computed in float32 regardless of input dtype (bf16-safe), cast back on exit —
XLA fuses the whole thing into neighboring ops on TPU so there is no reason
for a handwritten kernel here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-5):
    orig_dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(orig_dtype)
