"""First-party Pallas TPU flash attention (forward + backward).

Design (TPU-first, not a port — the reference ships no kernels at all; its
GPU analogue would be a CUDA flash kernel inside user containers):

- **Online softmax** over KV blocks: O(S) memory, no [S, S] logits —
  the long-context path SURVEY.md §5 requires.
- **GQA-native**: the grid iterates query heads; K/V blocks are indexed by
  ``kv_head = head // group`` directly in the BlockSpec index map, so
  grouped KV heads are never materialized ``repeat``-ed (the stock
  ``jax.experimental.pallas.ops.tpu.flash_attention`` needs H == KV_H and
  forces an O(S·H·D) repeat for GQA).
- **Flash backward**: saves only the per-row logsumexp; recomputes P
  blockwise in two kernels (dq; dk/dv fused per KV block, summing over the
  query-head group).
- f32 softmax/accumulation regardless of input dtype (MXU takes bf16 in,
  f32 out via ``preferred_element_type``).

Layout contract: q [B, S, H, D]; k/v [B, S, KV_H, D] — transposed to
[B, H, S, D] internally so each (head, seq-block) tile is contiguous.
Sequence lengths must divide the block sizes (the wrapper clamps blocks to
the sequence length); D should be a multiple of 128 for MXU tiling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _mask(i, j, block_q, block_kv, causal, kv_len):
    """Validity mask for an (i, j) tile: KV padding rows are always masked;
    the causal triangle additionally when ``causal``. ``kv_len`` is the real
    (pre-padding) KV length — a static compile-time constant."""
    kv_pos = j * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    m = kv_pos < kv_len
    if causal:
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        m = jnp.logical_and(m, q_pos >= kv_pos)
    return m


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale, block_q, block_kv, causal, seq_kv, kv_len):
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, D]
    d = q.shape[-1]

    n_kv = seq_kv // block_kv
    if causal:
        # blocks strictly above the diagonal contribute nothing
        n_kv = jnp.minimum(
            n_kv, jax.lax.div((i + 1) * block_q + block_kv - 1, block_kv))

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        v = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # [bq, bkv]
        s = jnp.where(_mask(i, j, block_q, block_kv, causal, kv_len),
                      s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))

    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse stored 8-sublane-replicated ([..., 8, block_q]) so the block shape
    # meets the TPU (8, 128) tile-alignment rule for outputs
    lse_ref[0, 0] = jnp.broadcast_to(
        (m + jnp.log(l))[None, :], (8, block_q))


def _fwd(q, k, v, causal, block_q, block_kv, kv_len, interpret):
    """q/k/v in [B, H|KVH, S, D] (padded to block multiples).
    Returns (o [B,H,S,D], lse [B,H,8,S])."""
    b, h, sq, d = q.shape
    _, kvh, skv, _ = k.shape
    g = h // kvh
    scale = 1.0 / (d ** 0.5)
    grid = (b, h, sq // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
        causal=causal, seq_kv=skv, kv_len=kv_len,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda bi, hi, i: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda bi, hi, i: (bi, hi // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda bi, hi, i: (bi, hi, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 8, sq), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward: dq kernel (grid over query blocks), dk/dv kernel (KV blocks)
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, block_q, block_kv, causal, seq_kv, kv_len):
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                  # [bq, D]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, 0, :]                            # sublane 0 of [8, bq]
    delta = delta_ref[0, 0, 0, :]
    d = q.shape[-1]

    n_kv = seq_kv // block_kv
    if causal:
        n_kv = jnp.minimum(
            n_kv, jax.lax.div((i + 1) * block_q + block_kv - 1, block_kv))

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = jnp.where(_mask(i, j, block_q, block_kv, causal, kv_len),
                      s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                     # [bq, bkv]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kv, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, block_q, block_kv, causal,
                groups, kv_len):
    """Grid (b, kvh, j, i): the query-block loop lives in the GRID (minor
    dim i), with dk/dv revisit-accumulated across i — so VMEM holds one
    [g, block_q, D] q/do window instead of the whole [g, S, D] sequence
    (at S=8k the full-sequence window alone was 2×16 MB double-buffered,
    overflowing v5p VMEM)."""
    j = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    def contribute():
        k = k_ref[0, 0].astype(jnp.float32)              # [bkv, D]
        v = v_ref[0, 0].astype(jnp.float32)
        d = k.shape[-1]
        dk = jnp.zeros((block_kv, d), jnp.float32)
        dv = jnp.zeros((block_kv, d), jnp.float32)
        for g in range(groups):                          # static unroll
            q = q_ref[0, g].astype(jnp.float32)          # [bq, D]
            do = do_ref[0, g].astype(jnp.float32)
            lse = lse_ref[0, g, 0, :]
            delta = delta_ref[0, g, 0, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            s = jnp.where(_mask(i, j, block_q, block_kv, causal, kv_len),
                          s, NEG_INF)
            p = jnp.exp(s - lse[:, None])                # [bq, bkv]
            dv = dv + jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None]) * scale
            dk = dk + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        dk_ref[0, 0] += dk
        dv_ref[0, 0] += dv

    if causal:
        # q block i only reaches kv block j when its last row is at or
        # below the diagonal; skipped steps cost one DMA, zero compute
        pl.when((i + 1) * block_q - 1 >= j * block_kv)(contribute)
    else:
        contribute()


def _bwd(causal, block_q, block_kv, kv_len, interpret, res, do):
    q, k, v, o, lse = res                                # lse: [B, H, S] f32
    b, h, sq, d = q.shape
    _, kvh, skv, _ = k.shape
    g = h // kvh
    scale = 1.0 / (d ** 0.5)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # 8-sublane replication only at the kernel boundary (tile alignment);
    # residuals above stay [B, H, S]
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, sq))
    lse8 = jnp.broadcast_to(lse[:, :, None, :], (b, h, 8, sq))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
            causal=causal, seq_kv=skv, kv_len=kv_len),
        grid=(b, h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda bi, hi, i: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda bi, hi, i: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda bi, hi, i: (bi, hi, 0, i)),
            pl.BlockSpec((1, 1, 8, block_q), lambda bi, hi, i: (bi, hi, 0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)

    # q/do tile over BOTH head-group and seq (grid dim i); dk/dv blocks are
    # revisited across i (out index map ignores i) and accumulate in place
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
            causal=causal, groups=g, kv_len=kv_len),
        grid=(b, kvh, skv // block_kv, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, g, block_q, d),
                         lambda bi, hi, j, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda bi, hi, j, i: (bi, hi, j, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda bi, hi, j, i: (bi, hi, j, 0)),
            pl.BlockSpec((1, g, block_q, d),
                         lambda bi, hi, j, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, g, 8, block_q),
                         lambda bi, hi, j, i: (bi, hi, 0, i)),
            pl.BlockSpec((1, g, 8, block_q),
                         lambda bi, hi, j, i: (bi, hi, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda bi, hi, j, i: (bi, hi, j, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda bi, hi, j, i: (bi, hi, j, 0)),
        ],
        out_shape=[
            # f32: the blocks accumulate IN PLACE across the i grid dim —
            # bf16 outputs would round the running sum every revisit
            jax.ShapeDtypeStruct((b, kvh, skv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, skv, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_kv, kv_len, interpret):
    o, _ = _fwd(q, k, v, causal, block_q, block_kv, kv_len, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_kv, kv_len, interpret):
    o, lse8 = _fwd(q, k, v, causal, block_q, block_kv, kv_len, interpret)
    return o, (q, k, v, o, lse8[:, :, 0, :])   # residual lse is [B, H, S]


_flash.defvjp(_flash_fwd, _bwd)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if not pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 512,
                    block_kv: int = 512, interpret: bool = False):
    """Flash attention. q: [B, S, H, D]; k/v: [B, S, KV_H, D] -> [B, S, H, D].

    GQA handled natively (H % KV_H == 0); KV heads are never repeated.
    Arbitrary sequence lengths: inputs are zero-padded to block multiples
    and padded KV positions are masked inside the kernels (padding/slicing
    sits outside the custom_vjp, so gradients transpose correctly).
    ``interpret=True`` runs the Pallas interpreter (CPU tests).
    """
    b, sq, h, d = q.shape
    _, skv, kvh, _ = k.shape
    if h % kvh:
        raise ValueError(f"H={h} not a multiple of KV_H={kvh}")
    # Clamp blocks to the (rounded-up) sequence length, keeping TPU tiling
    # alignment: short sequences round up to one 128-lane block and any
    # caller-supplied block stays a multiple of 8 sublanes; the zero-pad +
    # in-kernel masking absorbs the extra rows.
    block_q = _round_up(min(block_q, _round_up(sq, 128)), 8)
    block_kv = _round_up(min(block_kv, _round_up(skv, 128)), 128)
    qt = _pad_seq(q.transpose(0, 2, 1, 3), block_q)    # [B, H, S', D]
    kt = _pad_seq(k.transpose(0, 2, 1, 3), block_kv)
    vt = _pad_seq(v.transpose(0, 2, 1, 3), block_kv)
    o = _flash(qt, kt, vt, causal, block_q, block_kv, skv, interpret)
    return o[:, :, :sq, :].transpose(0, 2, 1, 3)
