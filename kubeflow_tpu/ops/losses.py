"""Loss functions. Cross-entropy in f32 with optional z-loss, masking, and
no [B,S,V] float32 materialization beyond what XLA needs (logsumexp fusion)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits, labels, mask=None, z_loss: float = 0.0):
    """Token-level CE. logits: [..., V] (any dtype), labels: [...] int32.

    Returns (mean_loss, aux) where aux has 'total_weight' for correct
    cross-data-parallel averaging and 'z_loss' if enabled.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    loss = logz - label_logit
    if z_loss:
        loss = loss + z_loss * jnp.square(logz)
    if mask is None:
        mask = jnp.ones_like(loss)
    mask = mask.astype(jnp.float32)
    total = jnp.maximum(mask.sum(), 1.0)
    return (loss * mask).sum() / total, {
        "total_weight": total,
        "sum_loss": (loss * mask).sum(),
    }


def accuracy(logits, labels, mask=None):
    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels).astype(jnp.float32)
    if mask is None:
        return correct.mean()
    mask = mask.astype(jnp.float32)
    return (correct * mask).sum() / jnp.maximum(mask.sum(), 1.0)
