from kubeflow_tpu.ops.attention import attention, decode_attention
from kubeflow_tpu.ops.losses import accuracy, softmax_cross_entropy
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import apply_rope, rope_frequencies
