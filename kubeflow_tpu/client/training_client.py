"""TrainingClient — the Python SDK over the job layer.

Parity with the reference SDK's `TrainingClient` surface (SURVEY.md §2.1:
create_job / get_job / get_job_logs / wait_for_job_conditions / delete_job,
plus the high-level `train()` sugar), minus the kubernetes client: the
transport is a JobController, which in production fronts a real cluster and
in tests fronts Fake/LocalProcess clusters.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from kubeflow_tpu.api.types import (
    ConditionType, JobSpec, RunPolicy, TPUSpec, jax_job,
)
from kubeflow_tpu.controller.cluster import LocalProcessCluster
from kubeflow_tpu.controller.reconciler import JobController, pod_name


class TrainingClient:
    def __init__(self, controller: JobController, namespace: str = "default"):
        self.controller = controller
        self.namespace = namespace

    def create_job(self, job: JobSpec) -> JobSpec:
        # "default" is JobSpec's unset sentinel: such jobs land in the
        # client's namespace so create/get/wait all use the same key.
        if not job.namespace or job.namespace == "default":
            job.namespace = self.namespace
        submitted = self.controller.submit(job)
        self.controller.reconcile(job.namespace, job.name)
        return submitted

    def create_jax_job(
        self,
        name: str,
        *,
        workers: int = 1,
        command: Optional[Sequence[str]] = None,
        tpu: Optional[TPUSpec] = None,
        mesh: Optional[dict] = None,
        env: Optional[dict] = None,
        run_policy: Optional[RunPolicy] = None,
    ) -> JobSpec:
        job = jax_job(
            name, workers=workers, command=list(command or []), tpu=tpu,
            mesh=mesh, env=env, run_policy=run_policy, namespace=self.namespace,
        )
        return self.create_job(job)

    def train(
        self,
        name: str,
        func: Callable,
        func_args: Optional[dict] = None,
        *,
        workers: int = 1,
        tpu: Optional[TPUSpec] = None,
        mesh: Optional[dict] = None,
        env: Optional[dict] = None,
        run_policy: Optional[RunPolicy] = None,
    ) -> JobSpec:
        """The reference SDK's high-level ``train()`` sugar: ship a
        self-contained Python function as the worker command of a JAXJob.

        Like the reference, ``func`` must be importable-free-standing: its
        source is extracted and templated into the container command, so
        every import it needs goes INSIDE the function body. ``func_args``
        must be JSON-serializable.
        """
        import inspect
        import json
        import sys
        import textwrap

        src = textwrap.dedent(inspect.getsource(func))
        if func.__name__.startswith("<"):
            raise ValueError("train() needs a named def, not a lambda")
        payload = json.dumps(func_args or {})
        script = (
            f"{src}\n"
            f"import json as _kft_json\n"
            f"{func.__name__}(**_kft_json.loads({payload!r}))\n"
        )
        return self.create_jax_job(
            name, workers=workers, command=[sys.executable, "-c", script],
            tpu=tpu, mesh=mesh, env=env, run_policy=run_policy,
        )

    def get_job(self, name: str) -> Optional[JobSpec]:
        return self.controller.get(self.namespace, name)

    def get_job_conditions(self, name: str):
        job = self.get_job(name)
        return job.status.conditions if job else []

    def wait_for_job_conditions(
        self,
        name: str,
        expected: Sequence[ConditionType] = (
            ConditionType.SUCCEEDED, ConditionType.FAILED,
        ),
        timeout: float = 300.0,
        poll: float = 0.2,
        callback: Optional[Callable[[JobSpec], None]] = None,
    ) -> JobSpec:
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.controller.reconcile(self.namespace, name)
            if job is None:
                raise KeyError(f"job {name} not found")
            if callback:
                callback(job)
            if job.status.condition() in expected:
                return job
            time.sleep(poll)
        raise TimeoutError(f"job {name}: no condition in {expected} after {timeout}s")

    def get_job_logs(self, name: str, replica_type: str = "Worker", index: int = 0) -> str:
        job = self.get_job(name)
        if job is None:
            raise KeyError(name)
        cluster = self.controller.cluster
        if isinstance(cluster, LocalProcessCluster):
            return cluster.pod_log(self.namespace, pod_name(job, replica_type, index))
        return ""

    def delete_job(self, name: str) -> None:
        self.controller.delete(self.namespace, name)
