"""Spans + per-process collector + W3C-traceparent propagation helpers.

A span is (name, t0, t1, attrs, parent) on a wall-clock timeline —
wall-clock, not monotonic, because spans from MANY processes (router,
model server, workers, operator) merge into one trace and only epoch
time is comparable across them. The collector is a lock-fenced ring
buffer: observation must be unconditionally cheap and bounded, so old
closed spans are overwritten (counted) rather than ever growing a list
— the same discipline the CanaryGate histogram fix applies to latencies.

Context propagation uses the W3C traceparent wire format
(``00-<32hex trace>-<16hex span>-01``) carried as an HTTP header AND as
a ``traceparent`` request parameter, so both the stdlib HTTP surfaces
and the in-process backends (router fronting a Model directly) chain
spans the same way.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
import uuid
from typing import Optional, Union

TRACEPARENT_HEADER = "traceparent"


def new_trace_id() -> str:
    return uuid.uuid4().hex                       # 32 hex chars


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]                  # 16 hex chars


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value) -> Optional[tuple[str, str]]:
    """-> (trace_id, span_id), or None for anything malformed. Tolerant:
    propagation must never fail a request over a bad header."""
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        t, s = int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if t == 0 or s == 0:                          # all-zero ids are invalid
        return None
    return trace_id.lower(), span_id.lower()


@dataclasses.dataclass
class Span:
    """One timed operation. ``t1 is None`` while open; ``attrs`` is free-
    form (counts, replica names, error tags). ``proc``/``tid`` are the
    Perfetto track the exporter places the span on."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    t0: float
    t1: Optional[float] = None
    attrs: dict = dataclasses.field(default_factory=dict)
    proc: str = ""
    tid: int = 0

    def traceparent(self) -> str:
        """The propagation header for children of THIS span."""
        return format_traceparent(self.trace_id, self.span_id)

    def duration(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> dict:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "t0": self.t0, "t1": self.t1, "attrs": dict(self.attrs),
            "proc": self.proc, "tid": self.tid,
        }


Parent = Union[Span, str, tuple, None]


def span_in_trace(span: dict, trace_id: str) -> bool:
    """THE trace-membership rule (shared by collector and exporter): a
    span belongs to a trace when it owns the id, or carries it in
    ``attrs.trace_ids`` — how engine-level dispatches covering several
    requests advertise every trace they served."""
    return (span.get("trace_id") == trace_id
            or trace_id in (span.get("attrs", {}).get("trace_ids") or ()))


class SpanCollector:
    """Lock-fenced ring buffer of closed spans + the set of open ones.

    ``start`` -> ``end`` (or the ``span(...)`` context manager) is the
    whole API surface instrumented code touches. Memory is O(capacity):
    when the ring wraps, the oldest closed span is overwritten and
    ``dropped`` counts it. ``abort_open`` closes every open span (of one
    trace, or all) with an ``aborted`` attr — the contract that keeps a
    request whose owner died mid-flight from leaking an unclosed span
    into the export.
    """

    def __init__(self, capacity: int = 4096, proc: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.proc = proc or f"pid{os.getpid()}"
        self._lock = threading.Lock()
        self._ring: list[Optional[Span]] = [None] * self.capacity
        self._next = 0                 # total closed spans ever appended
        self.dropped = 0
        self._open: dict[str, Span] = {}

    # ------------------------------------------------------- lifecycle --

    def start(self, name: str, *, parent: Parent = None,
              trace_id: Optional[str] = None,
              attrs: Optional[dict] = None) -> Span:
        """Open a span. ``parent`` may be a Span, a traceparent string,
        or a ``(trace_id, span_id)`` tuple; with no parent and no
        ``trace_id`` the span roots a new trace."""
        parent_id = None
        if isinstance(parent, Span):
            trace_id = trace_id or parent.trace_id
            parent_id = parent.span_id
        elif isinstance(parent, str):
            ctx = parse_traceparent(parent)
            if ctx is not None:
                trace_id = trace_id or ctx[0]
                parent_id = ctx[1]
        elif isinstance(parent, tuple) and len(parent) == 2:
            trace_id = trace_id or parent[0]
            parent_id = parent[1]
        span = Span(name=name, trace_id=trace_id or new_trace_id(),
                    span_id=new_span_id(), parent_id=parent_id,
                    t0=time.time(), attrs=dict(attrs or {}),
                    proc=self.proc, tid=threading.get_ident())
        with self._lock:
            self._open[span.span_id] = span
        return span

    def end(self, span: Span, **attrs) -> Span:
        """Close a span (idempotent, fenced by the collector lock): two
        racing enders — e.g. a client-abort thread and the engine step
        thread both seeing ``t1 is None`` — append exactly ONE ring
        entry; the loser's attrs are dropped with the race, never
        half-merged over the winner's."""
        with self._lock:
            if self._open.pop(span.span_id, None) is None:
                return span              # already ended (or foreign)
            if span.t1 is None:
                span.t1 = time.time()
            span.attrs.update(attrs)
            if self._next >= self.capacity:
                self.dropped += 1
            self._ring[self._next % self.capacity] = span
            self._next += 1
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, parent: Parent = None,
             trace_id: Optional[str] = None, attrs: Optional[dict] = None):
        s = self.start(name, parent=parent, trace_id=trace_id, attrs=attrs)
        try:
            yield s
        except BaseException as e:
            if s.t1 is None:
                self.end(s, error=type(e).__name__)
            raise
        finally:
            if s.t1 is None:
                self.end(s)

    def abort_open(self, trace_id: Optional[str] = None,
                   reason: str = "abort") -> int:
        """Close every open span (of ``trace_id``, or all): the span
        becomes a normal closed span with ``aborted=<reason>`` so traces
        of aborted/failed requests stay coherent. Returns the count."""
        with self._lock:
            victims = [s for s in self._open.values()
                       if trace_id is None or s.trace_id == trace_id]
        for s in victims:
            self.end(s, aborted=reason)
        return len(victims)

    # --------------------------------------------------------- reading --

    @property
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def snapshot(self) -> list[dict]:
        """Closed spans, oldest first (at most ``capacity``)."""
        with self._lock:
            n = min(self._next, self.capacity)
            start = self._next - n
            spans = [self._ring[(start + i) % self.capacity]
                     for i in range(n)]
        return [s.to_dict() for s in spans if s is not None]

    def spans_for(self, trace_id: str) -> list[dict]:
        """Closed spans belonging to one trace (the shared
        ``span_in_trace`` membership rule)."""
        return [s for s in self.snapshot() if span_in_trace(s, trace_id)]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._next = 0
            self.dropped = 0
            self._open.clear()


_global = SpanCollector()


def collector() -> SpanCollector:
    """The per-process default collector every instrumented surface
    (engine, server, router) records into unless handed its own."""
    return _global
