"""Span export (Chrome trace events / Perfetto) + operator job traces.

Two producers feed one consumer format:

- data plane: the per-process ``SpanCollector`` rings (router, model
  server, engine) — ``merge_spans`` + ``chrome_trace`` turn them into a
  single JSON document ``chrome://tracing`` and https://ui.perfetto.dev
  load directly (trace-event format, "X" complete events, microsecond
  timestamps, one pid per producer process).
- control plane: workers report phase timestamps (and optional explicit
  spans) over the heartbeat POST; the reconciler logs recovery events.
  ``build_job_trace`` merges both into span dicts per job — the
  operator serves it at ``/apis/v1/trace/{ns}/{job}`` and the recovery
  bench asserts its durations against the measured ``recovery_seconds``
  phases.
"""

from __future__ import annotations

import hashlib
import json
from typing import Iterable, Optional

from kubeflow_tpu.obs.trace import new_span_id, span_in_trace

# ------------------------------------------------------ chrome export --


def merge_spans(*span_lists: Iterable[dict]) -> list[dict]:
    """Concatenate span dicts from many collectors/processes, ordered by
    start time (the exporter's input contract)."""
    out: list[dict] = []
    for spans in span_lists:
        out.extend(spans)
    out.sort(key=lambda s: s.get("t0", 0.0))
    return out


def spans_for(spans: Iterable[dict], trace_id: str) -> list[dict]:
    """Filter merged spans to one trace (the shared ``span_in_trace``
    membership rule — engine dispatches covering several requests carry
    their traces in ``attrs.trace_ids``)."""
    return [s for s in spans if span_in_trace(s, trace_id)]


def chrome_trace(spans: Iterable[dict]) -> dict:
    """Span dicts -> a Chrome-trace-event document (Perfetto-loadable).

    Every closed span becomes one complete ("X") event; open spans are
    skipped (the collector's abort contract is supposed to have closed
    them). Each distinct ``proc`` string becomes a pid with a
    process_name metadata event so Perfetto labels the tracks."""
    spans = list(spans)
    procs: dict[str, int] = {}
    events: list[dict] = []
    for s in spans:
        if s.get("t1") is None:
            continue
        proc = s.get("proc") or "process"
        pid = procs.setdefault(proc, len(procs) + 1)
        args = {k: v for k, v in (s.get("attrs") or {}).items()}
        args["trace_id"] = s.get("trace_id")
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        events.append({
            "name": s.get("name", "span"),
            "ph": "X",
            "ts": s["t0"] * 1e6,                  # microseconds
            "dur": max(0.0, (s["t1"] - s["t0"]) * 1e6),
            "pid": pid,
            "tid": int(s.get("tid") or 0) % 100000,
            "cat": s.get("name", "span").split(".")[0],
            "args": args,
        })
    for proc, pid in procs.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": proc}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Iterable[dict]) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(spans), f)
    return path


def validate_trace(spans: Iterable[dict]) -> list[str]:
    """Coherence lint for a span list: every span closed, every
    parent_id resolvable WITHIN the list or explicitly external (the
    trace root's parent from another process). Returns problems."""
    spans = list(spans)
    ids = {s.get("span_id") for s in spans}
    external = {s.get("attrs", {}).get("external_parent")
                for s in spans}
    problems = []
    for s in spans:
        if s.get("t1") is None:
            problems.append(f"span {s.get('name')} never closed")
        p = s.get("parent_id")
        if p is not None and p not in ids and p not in external:
            problems.append(
                f"span {s.get('name')} has orphan parent {p}")
    return problems


# ---------------------------------------------------- operator traces --

# consecutive worker phase stamps -> span names; start resolves the
# FIRST present key (runs without checkpointing have no restore_done,
# smoke runs have no state_init_done)
_WORKER_SEGMENTS = (
    ("worker.imports", ("proc_start",), "imports_done"),
    ("worker.rendezvous", ("imports_done",), "rendezvous_done"),
    ("worker.state_init", ("rendezvous_done",), "state_init_done"),
    ("worker.restore", ("state_init_done", "rendezvous_done"),
     "restore_done"),
    ("worker.compile",
     ("restore_done", "state_init_done", "rendezvous_done"),
     "compile_done"),
    ("worker.first_step", ("compile_done", "rendezvous_done"),
     "first_step_done"),
    # profile_start is stamped by the worker at the REAL
    # jax.profiler.start_trace time; first_step_done is only the
    # legacy fallback for stamps predating it
    ("worker.profile", ("profile_start", "first_step_done"),
     "profile_done"),
)


def job_trace_id(namespace: str, name: str, uid: str) -> str:
    """Deterministic trace id for a job incarnation: every merger (two
    operators, a restarted one) labels the same job with the same id."""
    return hashlib.sha256(
        f"{namespace}/{name}/{uid}".encode()).hexdigest()[:32]


def _span(name, trace_id, t0, t1, parent=None, attrs=None, proc=""):
    return {"name": name, "trace_id": trace_id, "span_id": new_span_id(),
            "parent_id": parent, "t0": float(t0), "t1": float(t1),
            "attrs": dict(attrs or {}), "proc": proc, "tid": 0}


def _segments(ph: dict, trace_id: str, parent: str, pod: str) -> list:
    out = []
    for name, starts, end in _WORKER_SEGMENTS:
        if end not in ph:
            continue
        t0 = next((ph[k] for k in starts if k in ph), None)
        if t0 is None or ph[end] < t0:
            continue
        out.append(_span(name, trace_id, t0, ph[end], parent=parent,
                         attrs={"pod": pod}, proc=f"worker:{pod}"))
    return out


def build_job_trace(namespace: str, name: str, uid: str,
                    phase_reports: dict[str, dict],
                    recovery_events: Optional[list[dict]] = None,
                    worker_spans: Optional[dict[str, list]] = None
                    ) -> list[dict]:
    """Operator-side merge: per-pod phase stamps (heartbeat transport) +
    reconciler recovery events (+ any spans workers POSTed explicitly)
    -> one job trace.

    Per pod: a ``worker:{pod}`` root span covering its stamps, child
    segment spans per consecutive stamp pair; non-timestamp stamps
    (depot_hit, resumed_from_step, profile_dir) ride the root's attrs.
    Per ``replacement`` recovery event, the bench's recovery phases are
    reproduced as spans — claim (detection -> replacement process
    alive), load.imports, rendezvous, load.acquire (restore + depot
    deserialize / compile), first_step_after — durations the bench
    asserts against its own ``recovery_seconds`` decomposition. The
    ``detect`` phase needs the kill wall-time only the chaos injector
    knows, so it stays bench-side. Refusal/failure events become
    zero-length instant spans: a replacement that died mid-claim leaves
    a coherent trace, not a hole."""
    trace_id = job_trace_id(namespace, name, uid)
    events = list(recovery_events or [])
    spans: list[dict] = []
    all_ts = [e["t"] for e in events if isinstance(e.get("t"), (int, float))]

    # job root span (the trace anchor every parent chain resolves to)
    pod_roots: dict[str, str] = {}
    for pod, ph in sorted(phase_reports.items()):
        ts = [v for v in ph.values() if isinstance(v, (int, float))
              and v > 1e9]           # timestamps, not counters/stamps
        if not ts:
            continue
        all_ts.extend(ts)
    for posted in (worker_spans or {}).values():
        # explicit worker spans anchor the trace too: a job whose ONLY
        # observations are POSTed spans must not export empty
        all_ts.extend(s["t0"] for s in posted)
        all_ts.extend(s["t1"] for s in posted)
    if not all_ts:
        return []
    root = _span(f"job:{name}", trace_id, min(all_ts), max(all_ts),
                 attrs={"namespace": namespace, "job": name, "uid": uid},
                 proc="operator")
    spans.append(root)

    for pod, ph in sorted(phase_reports.items()):
        ts = {k: v for k, v in ph.items()
              if isinstance(v, (int, float)) and v > 1e9}
        if not ts:
            continue
        extras = {k: v for k, v in ph.items() if k not in ts}
        pod_root = _span(f"worker:{pod}", trace_id, min(ts.values()),
                         max(ts.values()), parent=root["span_id"],
                         attrs={"pod": pod, **extras},
                         proc=f"worker:{pod}")
        pod_roots[pod] = pod_root["span_id"]
        spans.append(pod_root)
        spans.extend(_segments(ts, trace_id, pod_root["span_id"], pod))

    # recovery events: instant spans for every logged event, plus the
    # phase spans for each replacement that has a matching set of
    # replacement-worker stamps (restore_done marks the takeover pod)
    for e in events:
        t = e.get("t")
        if not isinstance(t, (int, float)):
            continue
        attrs = {k: v for k, v in e.items() if k != "t"}
        spans.append(_span(f"recovery.{e.get('event', 'event')}",
                           trace_id, t, t, parent=root["span_id"],
                           attrs=attrs, proc="operator"))
    replacements = [e for e in events if e.get("event") == "replacement"]
    fails = [e["t"] for e in events if e.get("event") == "worker_failed"]
    # an incarnation's claim window ends at the NEXT failure/replacement
    # event: a later incarnation's stamps must never also satisfy an
    # earlier event (a replacement that died mid-claim would otherwise
    # duplicate the surviving incarnation's whole recovery span set and
    # stretch its claim span across the second failure)
    cuts = sorted({e["t"] for e in events
                   if e.get("event") in ("worker_failed", "replacement")
                   and isinstance(e.get("t"), (int, float))})
    _need = ("proc_start", "imports_done", "rendezvous_done",
             "compile_done", "first_step_done")
    for e in replacements:
        # the stamps of the pod that SERVED the replacement: on the kube
        # backend a claimed warm standby reports under its OWN pod name,
        # not the job identity in the event — so match by takeover time
        # (first full report whose proc_start falls in THIS event's
        # window), preferring an exact name match when one exists
        window_end = next((t for t in cuts if t > e["t"]), float("inf"))

        def _full(p):
            ph2 = phase_reports.get(p) or {}
            return (ph2 if all(k in ph2 for k in _need)
                    and e["t"] - 1e-3 <= ph2["proc_start"] < window_end
                    else None)

        ph = _full(e.get("pod"))
        pod = e.get("pod")
        if ph is None:
            candidates = [(p2, ph2) for p2 in sorted(phase_reports)
                          if (ph2 := _full(p2)) is not None]
            if candidates:
                pod, ph = min(candidates,
                              key=lambda c: c[1]["proc_start"])
        if ph is None:
            # replacement died before reporting (mid-claim), or its
            # stamps belong to a later incarnation: the instant event
            # above is the whole record — still a coherent trace
            continue
        t_detect = max((t for t in fails if t <= e["t"]),
                       default=e["t"])
        parent = pod_roots.get(pod, root["span_id"])
        rec = [
            ("recovery.claim", t_detect, ph["proc_start"]),
            ("recovery.load.imports", ph["proc_start"],
             ph["imports_done"]),
            ("recovery.rendezvous", ph["imports_done"],
             ph["rendezvous_done"]),
            ("recovery.load.acquire", ph["rendezvous_done"],
             ph["compile_done"]),
            ("recovery.first_step_after", ph["compile_done"],
             ph["first_step_done"]),
        ]
        # elastic-pipeline replacements stamp three more phases: the
        # boundary-snapshot load (rendezvous_done -> restore_done, carved
        # out of load.acquire), the replayed microbatch window (end of
        # compile -> the previously in-flight step's boundary), and the
        # first genuinely NEW step after replay — the bench's
        # pipeline.recovery decomposition reads these spans back
        if "restore_done" in ph:
            rec.append(("recovery.restore", ph["rendezvous_done"],
                        ph["restore_done"]))
        if "replay_done" in ph:
            rec.append(("recovery.replay_window", ph["compile_done"],
                        ph["replay_done"]))
            if "first_new_step_done" in ph:
                rec.append(("recovery.first_tick_after", ph["replay_done"],
                            ph["first_new_step_done"]))
        for rname, t0, t1 in rec:
            if t1 < t0:
                continue
            spans.append(_span(rname, trace_id, t0, t1, parent=parent,
                               attrs={"pod": pod,
                                      "incarnation": e.get("incarnation")},
                               proc="operator"))
    for pod, posted in sorted((worker_spans or {}).items()):
        for s in posted:
            attrs = dict(s.get("attrs") or {})
            span = _span(
                s.get("name", "worker.span"), trace_id, s["t0"], s["t1"],
                parent=pod_roots.get(pod, root["span_id"]),
                attrs=attrs, proc=f"worker:{pod}")
            # interleaved-1F1B: a stage worker multiplexes V virtual
            # chunks; give each chunk its own thread lane so the Perfetto
            # view shows the interleave instead of one flattened track
            try:
                span["tid"] = int(attrs.get("vstage", 0))
            except (TypeError, ValueError):
                pass
            spans.append(span)
    spans.sort(key=lambda s: s["t0"])
    return spans
