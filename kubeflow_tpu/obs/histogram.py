"""Log-bucketed Prometheus histograms — bounded-memory latency
distributions.

Why log buckets: request latencies span ~5 orders of magnitude (a 1 ms
cache-hit TTFT to a 60 s cold recovery), so exponentially-spaced bounds
give constant RELATIVE resolution (one factor-of-2 bucket) everywhere on
that range with a couple dozen counters. Percentiles read from buckets
are conservative (the bucket's upper bound — never an understatement),
which is exactly the bias an SLO gate wants.

Memory is O(buckets) forever — the fix for the CanaryGate's unbounded
``_latencies`` list, and the reason bench percentile math shares this
type instead of sorting raw sample lists.
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional, Sequence


def log_buckets(lo: float = 0.001, hi: float = 64.0,
                factor: float = 2.0) -> tuple[float, ...]:
    """Exponential bucket upper bounds from ``lo`` up to >= ``hi``."""
    if lo <= 0 or factor <= 1:
        raise ValueError("need lo > 0 and factor > 1")
    out = [float(lo)]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


# 1 ms .. ~65 s in factor-2 steps: 17 buckets covers every latency this
# system reports (TTFT, inter-token, e2e, recovery phases)
DEFAULT_BUCKETS = log_buckets()


class Histogram:
    """Thread-safe counting histogram with Prometheus semantics:
    ``observe`` increments the first bucket whose upper bound >= value
    (plus an implicit +Inf bucket), and the text exposition renders
    cumulative ``_bucket{le=...}`` lines + ``_sum`` + ``_count``."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("need at least one bucket bound")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0

    # -------------------------------------------------------- writing --

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's counts in (multi-replica/process
        aggregation). Bucket bounds must match."""
        if other.bounds != self.bounds:
            raise ValueError("bucket bounds differ; cannot merge")
        with other._lock:
            counts, s, n = list(other._counts), other._sum, other._count
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += s
            self._count += n

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0

    # -------------------------------------------------------- reading --

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation —
        conservative (>= the true percentile) by construction; 0.0 when
        empty. A quantile landing in the overflow (+Inf) bucket returns
        ``inf``: the histogram cannot bound those values, and reporting
        the largest finite bound instead would UNDERSTATE them — an SLO
        gate comparing p95 against a threshold above the last bound
        could then never trip."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q={q} not in [0, 1]")
        with self._lock:
            n = self._count
            counts = list(self._counts)
        if n == 0:
            return 0.0
        # rank int(q*n)+1 (capped): matches the sorted-list convention
        # xs[int(q*len(xs))] the raw-sample implementations used, so the
        # bucket answer is always >= the list answer it replaced
        target = min(n, int(q * n) + 1)
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                return (self.bounds[i] if i < len(self.bounds)
                        else float("inf"))
        return float("inf")

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def snapshot(self) -> dict:
        """JSON view: cumulative bucket counts keyed by upper bound,
        plus sum/count and the standard percentile trio. Percentiles in
        the overflow bucket clamp to the largest finite bound here —
        strict-JSON consumers can't carry Infinity — with the clamp made
        visible via ``overflow`` (the +Inf bucket's own count)."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        cum, buckets = 0, {}
        for bound, c in zip(self.bounds, counts):
            cum += c
            buckets[repr(bound)] = cum
        snap = {"buckets": buckets, "sum": round(s, 6), "count": n,
                "overflow": counts[-1]}
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            p = self.percentile(q)
            snap[key] = p if p != float("inf") else self.bounds[-1]
        return snap

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        bounds = sorted(float(b) for b in snap.get("buckets", {}))
        h = cls(buckets=bounds or DEFAULT_BUCKETS)
        prev = 0
        for i, b in enumerate(h.bounds):
            cum = int(snap["buckets"].get(repr(b), prev))
            h._counts[i] = cum - prev
            prev = cum
        h._count = int(snap.get("count", 0))
        h._counts[-1] = max(0, h._count - prev)       # +Inf remainder
        h._sum = float(snap.get("sum", 0.0))
        return h

    def render_lines(self, name: str,
                     labels: Optional[str] = None) -> list[str]:
        """Prometheus exposition sample lines for this histogram (no
        HELP/TYPE — the shared exposition helper owns those). ``labels``
        is a pre-rendered inner label string (``model="m"``) or None."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        inner = (labels + ",") if labels else ""
        lines = []
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            lines.append(f'{name}_bucket{{{inner}le="{bound}"}} {cum}')
        lines.append(f'{name}_bucket{{{inner}le="+Inf"}} {n}')
        tail = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{tail} {s}")
        lines.append(f"{name}_count{tail} {n}")
        return lines
