"""First-party observability: spans, histograms, Prometheus exposition,
Perfetto export.

The system's headline numbers (prefix-hit rate, recovery_seconds, warm
scale-up, tokens/s/stream) were measured through hand-rolled phase dicts
and flat counters; this package is the uniform instrument behind all of
them:

- ``trace``:     lightweight spans (name, t0/t1, attrs, parent) in a
                 lock-fenced per-process ring buffer, with
                 W3C-traceparent-style context propagation for the
                 stdlib HTTP surfaces (router -> model server -> engine,
                 heartbeat POSTs).
- ``histogram``: log-bucketed Prometheus histograms (``_bucket`` /
                 ``_sum`` / ``_count`` text exposition + bucket-resolved
                 percentiles) — bounded memory no matter the
                 observation count.
- ``expo``:      the ONE exposition helper every ``/metrics`` surface
                 renders through (``# HELP`` / ``# TYPE`` per family,
                 ``_total``-suffixed counters enforced) plus the
                 lint-style validator the test suite and smoke use.
- ``export``:    merge spans from many processes into Chrome-trace-event
                 JSON that Perfetto / chrome://tracing load directly,
                 and build operator-side job traces from heartbeat phase
                 reports + the reconciler recovery log.

Pure stdlib on purpose (like serving/scheduler.py): the control plane
must import this without dragging jax in.
"""

from kubeflow_tpu.obs.histogram import Histogram, log_buckets
from kubeflow_tpu.obs.trace import (
    Span, SpanCollector, collector, format_traceparent, parse_traceparent,
)

__all__ = [
    "Histogram", "log_buckets",
    "Span", "SpanCollector", "collector",
    "format_traceparent", "parse_traceparent",
]
