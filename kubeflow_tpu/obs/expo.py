"""The ONE Prometheus text-exposition helper — and its lint validator.

Every ``/metrics`` surface in this repo (operator daemon, model server)
renders through ``render_exposition`` so the format rules live in one
place instead of three hand-rolled f-string blocks:

- exactly one ``# HELP`` + ``# TYPE`` header per family, emitted before
  the family's first sample;
- counter families MUST end in ``_total`` (or be the ``_sum``/``_count``
  components of a timing pair) — enforced, a violation raises at render
  time instead of shipping a malformed family;
- histogram families MUST end in ``_seconds`` (every timing family in
  this repo measures seconds) and render the full cumulative
  ``_bucket``/``_sum``/``_count`` triplet via ``Histogram.render_lines``.

``validate_exposition`` is the matching lint used by the test suite and
the obs smoke: it re-parses scraped text and returns every violation,
so a counter rename or a hand-rolled exposition sneaking back in
regresses visibly.
"""

from __future__ import annotations

import re
from typing import Iterable, Optional, Union

from kubeflow_tpu.obs.histogram import Histogram

# counter component suffixes: _total for plain counters; _sum/_count are
# the monotonic halves of a timing pair or histogram
COUNTER_SUFFIXES = ("_total", "_sum", "_count")

# family name -> human help line (optional; a generic line otherwise)
HELP: dict[str, str] = {
    "kft_model_request_ttft_seconds":
        "Time to first token per request (enqueue -> first commit)",
    "kft_model_request_itl_seconds":
        "Inter-token latency per generated token (chunk-amortized)",
    "kft_model_request_e2e_seconds":
        "End-to-end request latency (enqueue -> finish)",
    # trial swarm (hpo/swarm.py SwarmTrialRunner + warm-pool reclaim arc)
    "kft_swarm_trials_running_total":
        "HPO trials that entered RUNNING (per experiment)",
    "kft_swarm_trials_succeeded_total":
        "HPO trials that finished with an objective value",
    "kft_swarm_trials_stopped_total":
        "HPO trials early-stopped/killed by the controller",
    "kft_swarm_pool_starvation_total":
        "Trials that cold-started because the warm pool was dry",
    "kft_swarm_reclaims_total":
        "Early-stopped trial pods returned to the warm pool as standbys",
    "kft_swarm_claim_seconds":
        "Trial submit -> worker exec latency (warm claim or cold path)",
    "kft_warm_pool_reclaims_total":
        "Claimed pods returned to standby (worker killed, token rotated)",
    "kft_warm_pool_reclaim_noops_total":
        "Reclaims of finished/dead/gone pods (counted no-op, never a crash)",
    # disaggregated serving (serving/disagg.py MigrationStats)
    "kft_disagg_migrations_total":
        "Completed prefill->decode paged-KV migrations",
    "kft_disagg_migrated_blocks_total":
        "Paged-KV blocks moved prefill->decode over the DCN transport",
    "kft_disagg_migration_failures_total":
        "Handoffs that fell back to local generation on the prefill pod",
    "kft_disagg_migration_retries_total":
        "KV sends retried after a transient no-capacity nack",
    "kft_disagg_migration_aborts_total":
        "Handoffs aborted mid-flight (released on both tiers)",
    "kft_disagg_handoffs_injected_total":
        "Handoffs admitted into a decode engine's slot map",
    "kft_disagg_imported_blocks_total":
        "Paged-KV blocks scattered into a decode pool from handoffs",
    "kft_disagg_handoff_rejects_total":
        "Handoffs a decode pod refused (pool full, bad payload)",
    "kft_disagg_duplicate_deliveries_total":
        "Duplicate kv frames answered by ack replay (idempotent)",
    "kft_disagg_releases_total":
        "Release frames that dropped an injected handoff",
    "kft_disagg_prefill_bypasses_total":
        "Requests that skipped the prefill tier on a full radix hit",
    "kft_disagg_export_seconds_total":
        "Cumulative device->host KV gather time across migrations",
    "kft_disagg_transfer_seconds_total":
        "Cumulative wire+inject time across migrations",
    "kft_disagg_bytes_sent_total":
        "Bytes of paged-KV payload sent over the migration transport",
    "kft_disagg_wire_seconds_total":
        "Cumulative socket round-trip time of kv frames",
    # elastic MPMD pipeline (parallel/mpmd.py ElasticStats)
    "kft_pipeline_recv_timeouts_total":
        "Stage recv_act/recv_grad waits that hit the recv timeout "
        "(KFT_PIPE_RECV_TIMEOUT_S) — a wedged or dead neighbor",
    "kft_pipeline_mailbox_poisons_total":
        "Microbatch windows aborted through the mailbox-poison path "
        "(sender-thread transport failures + epoch-bump signals)",
    "kft_pipeline_stale_frames_fenced_total":
        "Channel frames from a dead rendezvous incarnation dropped by "
        "the epoch fence (ingress mismatch + reform-time mailbox drain)",
}


def format_labels(**labels) -> Optional[str]:
    """The ONE inner-label-block builder for /metrics surfaces: sorted
    ``name="value"`` pairs with empty/None values dropped, or None when
    nothing survives (so ``model=``/``tier=`` compose identically on
    every family instead of each renderer hand-rolling f-strings)."""
    kept = {k: v for k, v in labels.items() if v not in (None, "")}
    if not kept:
        return None

    def esc(v) -> str:
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    return ",".join(f'{k}="{esc(v)}"' for k, v in sorted(kept.items()))

Sample = tuple[Optional[str], Union[float, Histogram, dict]]
Family = tuple[str, str, list[Sample]]


def _check_name(name: str, mtype: str) -> None:
    if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name):
        raise ValueError(f"invalid metric family name {name!r}")
    if mtype == "counter" and not name.endswith(COUNTER_SUFFIXES):
        raise ValueError(
            f"counter family {name!r} must end in _total (or _sum/_count)")
    if mtype == "histogram" and not name.endswith("_seconds"):
        raise ValueError(
            f"histogram family {name!r} must end in _seconds "
            "(timing families are measured in seconds)")


def render_exposition(families: Iterable[Family]) -> str:
    """Families -> Prometheus text. Each family is
    ``(name, type, samples)`` with type in counter|gauge|histogram and
    samples ``[(inner_label_str_or_None, value)]``; histogram sample
    values are ``Histogram`` objects or their ``snapshot()`` dicts."""
    lines: list[str] = []
    seen: set[str] = set()
    for name, mtype, samples in families:
        if mtype not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric type {mtype!r} for {name!r}")
        _check_name(name, mtype)
        if name in seen:
            raise ValueError(f"family {name!r} rendered twice")
        seen.add(name)
        lines.append(f"# HELP {name} "
                     f"{HELP.get(name, f'kubeflow_tpu {mtype}')}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            if mtype == "histogram":
                hist = (value if isinstance(value, Histogram)
                        else Histogram.from_snapshot(value))
                lines.extend(hist.render_lines(name, labels))
            else:
                tail = f"{{{labels}}}" if labels else ""
                lines.append(f"{name}{tail} {float(value)}")
    return "\n".join(lines) + "\n"


def family_of(sample_name: str) -> str:
    """Sample name -> family name (histogram components fold in)."""
    bare = sample_name.split("{", 1)[0]
    for suffix in ("_bucket", "_sum", "_count"):
        if bare.endswith(suffix):
            return bare[: -len(suffix)]
    return bare


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def validate_exposition(text: str) -> list[str]:
    """Lint a scraped /metrics body; returns problems ([] = valid).

    Checks: parsable sample lines; one HELP+TYPE per family before its
    first sample; counters end in _total/_sum/_count; histogram families
    end in _seconds with cumulative le-ordered buckets, a +Inf bucket
    equal to _count, and both _sum and _count present."""
    problems: list[str] = []
    types: dict[str, str] = {}
    helped: set[str] = set()
    # histogram family -> labelset -> [(le, cum)], count, sum-present
    hist: dict[str, dict[str, dict]] = {}
    samples_seen: set[str] = set()

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                problems.append(f"line {lineno}: HELP without text")
            else:
                helped.add(parts[2])
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                problems.append(f"line {lineno}: malformed TYPE: {line!r}")
                continue
            fam, mtype = parts[2], parts[3]
            if fam in types:
                problems.append(f"line {lineno}: duplicate TYPE for {fam}")
            if fam in samples_seen:
                problems.append(
                    f"line {lineno}: TYPE for {fam} after its samples")
            types[fam] = mtype
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {lineno}: unparsable sample: {line!r}")
            continue
        name, labelblock, value = m.group(1), m.group(2) or "", m.group(3)
        try:
            fval = float(value)
        except ValueError:
            problems.append(f"line {lineno}: non-numeric value: {line!r}")
            continue
        fam = family_of(name)
        samples_seen.add(fam)
        mtype = types.get(fam)
        if mtype is None:
            # a bare name that IS its own family (e.g. a gauge named
            # *_count would fold wrongly) — accept exact-name TYPE too
            mtype = types.get(name)
            if mtype is not None:
                fam = name
                samples_seen.add(fam)
        if mtype is None:
            problems.append(
                f"line {lineno}: sample {name!r} has no TYPE header")
            continue
        if fam not in helped:
            problems.append(f"line {lineno}: family {fam} missing HELP")
        if mtype == "counter":
            if not fam.endswith(COUNTER_SUFFIXES):
                problems.append(
                    f"counter family {fam} must end in _total/_sum/_count")
            if fval < 0:
                problems.append(f"line {lineno}: negative counter {fam}")
        if mtype == "histogram":
            if not fam.endswith("_seconds"):
                problems.append(
                    f"histogram family {fam} must end in _seconds")
            # group the series by its labels MINUS le: split the block
            # into name="value" pairs and drop le, so the grouping is
            # independent of label ORDER (a producer emitting le first
            # must not lint as a broken histogram) and an le-only block
            # matches the bare _sum/_count lines
            pairs = re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]'
                               r'|\\.)*)"', labelblock)
            kept = [f'{k}="{v}"' for k, v in pairs if k != "le"]
            labels = "{" + ",".join(sorted(kept)) + "}" if kept else ""
            entry = hist.setdefault(fam, {}).setdefault(
                labels, {"buckets": [], "count": None, "sum": False})
            if name.endswith("_bucket"):
                le = re.search(r'le="([^"]*)"', labelblock)
                if le is None:
                    problems.append(
                        f"line {lineno}: histogram bucket without le")
                else:
                    entry["buckets"].append((le.group(1), fval))
            elif name.endswith("_count"):
                entry["count"] = fval
            elif name.endswith("_sum"):
                entry["sum"] = True
            else:
                problems.append(
                    f"line {lineno}: stray histogram sample {name!r}")

    for fam, series in hist.items():
        for labels, entry in series.items():
            where = f"{fam}{labels or ''}"
            buckets = entry["buckets"]
            if not buckets:
                problems.append(f"{where}: histogram with no buckets")
                continue
            if buckets[-1][0] != "+Inf":
                problems.append(f"{where}: last bucket is not le=+Inf")
            finite = [float(le) for le, _ in buckets[:-1]]
            if finite != sorted(finite):
                problems.append(f"{where}: bucket bounds not ascending")
            counts = [c for _, c in buckets]
            if counts != sorted(counts):
                problems.append(f"{where}: bucket counts not cumulative")
            if entry["count"] is None:
                problems.append(f"{where}: missing _count")
            elif buckets[-1][1] != entry["count"]:
                problems.append(
                    f"{where}: +Inf bucket != _count "
                    f"({buckets[-1][1]} vs {entry['count']})")
            if not entry["sum"]:
                problems.append(f"{where}: missing _sum")
    return problems
