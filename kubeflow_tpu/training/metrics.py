"""Training metrics: in-process writer + scrape-free export.

Replaces the reference's Katib metrics-collector *sidecar* (stdout regex
parsing -> gRPC -> MySQL; SURVEY.md §2.3) with a native path: the training
loop writes typed scalars to a JSONL file / in-memory buffer that the tuner
and observability layers read directly. No stdout scraping.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any


class MetricsWriter:
    """Appends {"step": n, "ts": t, name: value, ...} records to a JSONL file
    (and keeps them in memory). Thread-safe; file is the cross-process contract
    used by the tune/ trial controller."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.records: list[dict] = []
        self._lock = threading.Lock()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def write(self, step: int, **metrics: Any):
        rec = {"step": int(step), "ts": time.time()}
        for k, v in metrics.items():
            rec[k] = float(v) if hasattr(v, "__float__") else v
        with self._lock:
            self.records.append(rec)
            if self.path:
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
        return rec

    def latest(self, name: str):
        for rec in reversed(self.records):
            if name in rec:
                return rec[name]
        return None


def read_metrics(path: str) -> list[dict]:
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # partial concurrent write; next read gets it
    return out


def objective_from_metrics(records: list[dict], name: str, mode: str = "min"):
    vals = [r[name] for r in records if name in r]
    if not vals:
        return None
    return min(vals) if mode == "min" else max(vals)
