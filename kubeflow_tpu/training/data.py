"""Data loading: sharded host->device feeding.

Provides a synthetic LM token stream (benchmarks, tests) and a generic
host-array feeder that places global batches onto the mesh with the
(data, fsdp) batch sharding. Multi-host: each process feeds only its local
shard via `jax.make_array_from_process_local_data`.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(("data", "fsdp")))


def put_batch(mesh: Mesh, batch):
    """Place a host pytree onto the mesh, sharded over the batch dim."""
    sh = batch_sharding(mesh)
    n_proc = jax.process_count()
    if n_proc == 1:
        return jax.device_put(batch, sh)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sh, x), batch
    )


def synthetic_lm_batches(
    vocab_size: int, global_batch: int, seq_len: int, seed: int = 0,
    start_step: int = 0,
) -> Iterator[dict]:
    """Infinite synthetic token batches: {"tokens": [B, S+1]} on host.

    Step-indexed: batch ``i`` is a pure function of ``(seed, i, process)``,
    so a resumed job can seek with ``start_step`` and see the exact same
    step->batch mapping (the deterministic data-resume contract of
    ``loop.fit``). Multi-host aware: yields only this process's slice.
    """
    n_proc = jax.process_count()
    local = global_batch // n_proc
    step = start_step
    while True:
        rng = np.random.default_rng([seed, step, jax.process_index()])
        yield {
            "tokens": rng.integers(
                0, vocab_size, (local, seq_len + 1), dtype=np.int32
            )
        }
        step += 1


def mnist_synthetic(batch: int, seed: int = 0) -> Iterator[dict]:
    """Synthetic MNIST-shaped batches (CPU baseline config, BASELINE.json:7)."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "image": rng.normal(size=(batch, 28, 28, 1)).astype(np.float32),
            "label": rng.integers(0, 10, (batch,), dtype=np.int32),
        }
