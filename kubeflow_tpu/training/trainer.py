"""pjit training loop: sharded train state, fused train step, grad accumulation.

The reference delegates all of this to user containers (SURVEY.md §2.7 — the
operator only does rendezvous); here it is a first-party framework feature.
One train step is a single jitted function with explicit in/out shardings; XLA
emits all collectives (gradient all-reduce over `data`+`fsdp`, weight
all-gathers for FSDP, TP collectives) from the sharding annotations.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.ops.losses import softmax_cross_entropy
from kubeflow_tpu.parallel import sharding as shd


@dataclasses.dataclass
class TrainerConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    grad_accum: int = 1
    # "adamw": full f32 m/v (2x params of state). "adafactor": factored
    # second moment (state ~ O(rows+cols)) — the memory-budget choice for
    # big models on small HBM (T5X-style default on TPU).
    optimizer: str = "adamw"
    rules: Mapping[str, object] | None = None   # logical->mesh rules override
    # Metric-key conventions for gradient accumulation (instead of hardcoding
    # the literal "tokens"): `weight_metric` names the metric holding each
    # microbatch's loss-normalization weight (token count for LM losses);
    # loss and grads are re-weighted by it so accumulation reproduces the
    # GLOBAL token-weighted mean even when mask density varies across
    # microbatches. `count_metrics` are summed across microbatches; all other
    # metrics are averaged.
    weight_metric: str = "tokens"
    count_metrics: tuple = ("tokens",)


def make_optimizer(cfg: TrainerConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, cfg.learning_rate, cfg.warmup_steps, max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )
    if cfg.optimizer == "adafactor":
        # no weight decay here: optax.adafactor applies weight_decay_rate
        # AFTER lr scaling (raw fraction per step — 0.1 would collapse the
        # params), unlike adamw's lr-scaled decay. Adafactor runs train
        # decay-free (the T5X-style default).
        return optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adafactor(schedule),
        )
    if cfg.optimizer != "adamw":
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2, weight_decay=cfg.weight_decay),
    )


class Trainer:
    """Builds and owns the sharded train state + compiled step.

    loss_fn(params, batch) -> (loss, metrics_dict). `batch` is a pytree whose
    leaves' leading dim is the global batch (sharded over data+fsdp).
    """

    def __init__(
        self,
        mesh: Mesh,
        init_params_fn: Callable[[jax.Array], Any],
        params_logical_axes,
        loss_fn: Callable[[Any, Any], tuple[jax.Array, dict]],
        config: TrainerConfig,
        donate_state: bool = True,
    ):
        self.mesh = mesh
        self.config = config
        self.loss_fn = loss_fn
        self.optimizer = make_optimizer(config)
        rules = config.rules or shd.DEFAULT_RULES

        self.param_specs = shd.tree_pspecs(params_logical_axes, rules)
        self.param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.param_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )
        self.batch_sharding = NamedSharding(
            mesh, PartitionSpec(("data", "fsdp"))
        )

        # init params directly into their shards (no host-side full copy)
        self._init_jit = jax.jit(init_params_fn, out_shardings=self.param_shardings)
        # optimizer.init only reads shapes, so jit does NOT propagate input
        # shardings to its outputs — compute explicit out_shardings: any opt
        # leaf that mirrors a param (adam mu/nu trees) inherits that param's
        # sharding, everything else (counts, empty states) is replicated.
        params_shape = jax.eval_shape(init_params_fn, jax.random.key(0))
        self.opt_shardings = self._opt_state_shardings(params_shape)
        self._opt_init = jax.jit(
            self.optimizer.init, out_shardings=self.opt_shardings
        )

        self.step_fn = self._build_step(donate_state)
        # AOT-compiled step installed by precompile(): same program, but
        # the compile happened eagerly (and possibly on another worker —
        # the executable-depot fast path) instead of inside step 1
        self._compiled_step = None
        self.params = None
        self.opt_state = None
        self.step = 0

    def _opt_state_shardings(self, params_shape):
        """Shardings for the optimizer state, matched by path suffix: optax
        wraps the params treedef inside its own states (mu/nu/...), so a
        param's path is a suffix of its mirror's path in the opt state."""
        opt_shapes = jax.eval_shape(self.optimizer.init, params_shape)
        is_sh = lambda x: isinstance(x, NamedSharding)
        p_sh = jax.tree_util.tree_flatten_with_path(
            self.param_shardings, is_leaf=is_sh)[0]
        p_shape = jax.tree_util.tree_flatten_with_path(params_shape)[0]
        by_path = {
            tuple(map(str, path)): (shape.shape, sh)
            for (path, sh), (_, shape) in zip(p_sh, p_shape)
        }
        replicated = NamedSharding(self.mesh, PartitionSpec())

        def pick(path, leaf):
            p = tuple(map(str, path))
            for i in range(len(p)):
                hit = by_path.get(p[i:])
                if hit is not None and hit[0] == leaf.shape:
                    return hit[1]
            return replicated

        return jax.tree_util.tree_map_with_path(pick, opt_shapes)

    def init_state(self, rng: jax.Array):
        self.params = self._init_jit(rng)
        self.opt_state = self._opt_init(self.params)
        self.step = 0
        return self.params

    def _build_step(self, donate: bool):
        optimizer = self.optimizer
        loss_fn = self.loss_fn
        accum = self.config.grad_accum
        mesh = self.mesh

        def grads_of(params, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            return loss, metrics, grads

        def step(params, opt_state, batch):
            if accum > 1:
                # split leading batch dim into [accum, micro, ...] and scan
                micro = jax.tree_util.tree_map(
                    lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]),
                    batch,
                )
                mb0 = jax.tree_util.tree_map(lambda x: x[0], micro)
                _, m_shapes, _ = jax.eval_shape(grads_of, params, mb0)

                # Each microbatch loss is a weighted mean (weight = its token
                # count, exposed via cfg.weight_metric). Accumulate
                # UN-normalized sums — loss·w, grads·w, Σw — and divide once,
                # so the result is the global token-weighted mean regardless
                # of how mask density varies across microbatches.
                weight_key = self.config.weight_metric

                def body(carry, mb):
                    g_acc, loss_acc, w_acc, m_acc = carry
                    loss, metrics, grads = grads_of(params, mb)
                    w = jnp.asarray(
                        metrics.get(weight_key, 1.0), jnp.float32)
                    g_acc = jax.tree_util.tree_map(
                        lambda a, g: a + g * w.astype(g.dtype), g_acc, grads)
                    m_acc = jax.tree_util.tree_map(jnp.add, m_acc, metrics)
                    return (g_acc, loss_acc + loss * w, w_acc + w, m_acc), None

                zeros_g = jax.tree_util.tree_map(jnp.zeros_like, params)
                zeros_m = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), m_shapes
                )
                (g_sum, loss_sum, w_sum, m_sum), _ = jax.lax.scan(
                    body, (zeros_g, 0.0, 0.0, zeros_m), micro
                )
                denom = jnp.maximum(w_sum, 1e-8)
                grads = jax.tree_util.tree_map(
                    lambda g: g / denom.astype(g.dtype), g_sum)
                loss = loss_sum / denom
                counts = set(self.config.count_metrics)
                metrics = {
                    k: (v if k in counts else v / accum)
                    for k, v in m_sum.items()
                }
            else:
                loss, metrics, grads = grads_of(params, batch)

            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            gnorm = optax.global_norm(grads)
            metrics = dict(metrics, loss=loss, grad_norm=gnorm)
            return params, opt_state, metrics

        donate_argnums = (0, 1) if donate else ()
        # shardings propagate from the arguments (params/opt_state placed at
        # init, batch placed by the data loader via self.batch_sharding)
        return jax.jit(step, donate_argnums=donate_argnums)

    def train_step(self, batch):
        # the mesh context MUST be live at trace time: the model's logical
        # activation constraints (parallel/sharding.constrain) resolve
        # PartitionSpecs against the ambient mesh and silently no-op
        # without one — which costs activation sharding (batch stays
        # data-sharded only, fsdp/tensor axes unused) on multichip
        fn = self._compiled_step if self._compiled_step is not None \
            else self.step_fn
        with self.mesh:
            self.params, self.opt_state, metrics = fn(
                self.params, self.opt_state, batch
            )
        self.step += 1
        return metrics

    def precompile(self, batch, depot=None, stats=None,
                   wait_s: float = 0.0) -> str:
        """Split compile from step 1: lower the train step for ``batch``'s
        shapes and compile it NOW — fetching the executable from an
        executable depot (``parallel/depot.py``) when one is given, and
        publishing it on a miss so the rest of the gang (and every
        warm-pool resubmit) deserializes instead of compiling. Requires
        ``init_state`` first; pins the batch shape subsequent
        ``train_step`` calls use. Returns the depot outcome ("hit" /
        "published" / "compiled" / "no_depot"); depot trouble NEVER
        raises — worst case is the compile this call was going to pay
        anyway."""
        if self.params is None:
            raise ValueError("precompile needs init_state() first")
        from kubeflow_tpu.parallel.depot import load_or_compile

        lowered = self.lower_step(self.params, self.opt_state, batch)
        self._compiled_step, outcome = load_or_compile(
            lowered, depot, mesh=self.mesh, stats=stats, wait_s=wait_s)
        return outcome

    def lower_step(self, params_shapes, opt_shapes, batch_shapes):
        """AOT entry (parallel/aot.py scale proofs): lower the train step
        under the mesh so activation constraints bind, without arrays."""
        with self.mesh:
            return self.step_fn.lower(params_shapes, opt_shapes, batch_shapes)


def lm_loss_fn(forward, cfg):
    """Next-token LM loss for a model `forward(params, tokens, cfg)`.

    Batch: {"tokens": [B, S+1] int32, "mask": optional [B, S+1]}.
    """

    moe = bool(getattr(cfg, "n_experts", 0))

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        if moe:
            logits, fwd_aux = forward(params, inputs, cfg, return_aux=True)
        else:
            logits = forward(params, inputs, cfg)
        mask = batch.get("mask")
        mask = mask[:, 1:] if mask is not None else None
        loss, aux = softmax_cross_entropy(
            logits, targets, mask, z_loss=getattr(cfg, "z_loss", 0.0)
        )
        metrics = {"tokens": aux["total_weight"]}
        if moe:
            loss = loss + fwd_aux["moe_aux"]
            metrics["moe_aux"] = fwd_aux["moe_aux"]
        return loss, metrics

    return loss_fn
