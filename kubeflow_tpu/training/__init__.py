from kubeflow_tpu.training.trainer import Trainer, TrainerConfig, lm_loss_fn, make_optimizer
from kubeflow_tpu.training.data import batch_sharding, put_batch, synthetic_lm_batches
from kubeflow_tpu.training.dataset import TokenDataset, write_token_shards
from kubeflow_tpu.training.metrics import MetricsWriter, objective_from_metrics, read_metrics
