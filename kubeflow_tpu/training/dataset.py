"""File-backed tokenized corpus: memory-mapped shards, deterministic
shuffle, exact step-indexed resume.

The data plane is owned by the framework (SURVEY.md §7 design stance — the
reference delegates data to user containers; here the trainer must be able
to run the BASELINE ladder on a real on-disk corpus). Storage follows the
mounted-bucket convention (`serving/storage.py`): a dataset is a directory
of ``*.tokens.npy`` shards — typically a GCS bucket fuse-mounted into the
pod — each a 1-D integer array of token ids.

Resume contract: batch ``i`` is a PURE function of ``(corpus, seq_len,
global_batch, seed, i, process)``. Examples are fixed ``seq_len+1`` windows
(never crossing shard boundaries); each epoch visits every window once in
an epoch-seeded permutation; step ``i`` takes the next ``global_batch``
entries of that infinite stream. ``loop.fit`` checkpoints the trainer step
and calls ``batches(start_step)`` on restore, so a killed-and-resumed job
continues the exact step->batch mapping of an uninterrupted one — the
kill-and-resume e2e in tests/test_dataset.py proves it over a real corpus.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional

import numpy as np

_SHARD_SUFFIX = ".tokens.npy"


def write_token_shards(path: str, tokens, shard_tokens: int = 1 << 22,
                       vocab_size: Optional[int] = None) -> list[str]:
    """Materialize a token stream as a shard directory.

    ``tokens``: any iterable of integer arrays/lists (documents or chunks);
    they are concatenated and split into ``shard_tokens``-sized shards.
    Streaming: at most one shard's worth of tokens is resident at a time,
    so a corpus far larger than host memory can be prepared (matching the
    reader's mmap stance). Returns the shard paths; writes
    ``dataset.json`` metadata alongside.
    """
    os.makedirs(path, exist_ok=True)
    paths: list[str] = []
    pending: list[np.ndarray] = []
    pending_n = total = 0

    def flush(n: int) -> None:
        nonlocal pending, pending_n
        if not pending:
            pending = [np.zeros(0, np.int32)]
        flat = np.concatenate(pending) if len(pending) != 1 else pending[0]
        p = os.path.join(path, f"shard-{len(paths):05d}{_SHARD_SUFFIX}")
        np.save(p, flat[:n])
        paths.append(p)
        rest = flat[n:]
        pending = [rest] if len(rest) else []
        pending_n = len(rest)

    for t in tokens:
        chunk = np.asarray(t, dtype=np.int32).ravel()
        pending.append(chunk)
        pending_n += len(chunk)
        total += len(chunk)
        while pending_n >= shard_tokens:
            flush(shard_tokens)
    if pending_n or not paths:
        flush(pending_n)
    with open(os.path.join(path, "dataset.json"), "w") as f:
        json.dump({"total_tokens": total,
                   "shards": len(paths),
                   "vocab_size": vocab_size}, f)
    return paths


class TokenDataset:
    """Memory-mapped reader over a token-shard directory.

    Shards are opened with ``mmap_mode='r'`` — no shard is ever resident in
    host RAM beyond the pages a batch touches, so a corpus far larger than
    memory streams at page-cache speed (the mounted-bucket read path).
    """

    def __init__(self, path: str, seq_len: int, seed: int = 0):
        names = sorted(n for n in os.listdir(path)
                       if n.endswith(_SHARD_SUFFIX))
        if not names:
            raise FileNotFoundError(
                f"no {_SHARD_SUFFIX} shards under {path!r}")
        self.path = path
        self.seq_len = int(seq_len)
        self.seed = int(seed)
        self._shards = [np.load(os.path.join(path, n), mmap_mode="r")
                        for n in names]
        # fixed windows of seq_len+1 tokens (inputs + shifted targets),
        # never crossing a shard boundary: window w of shard s starts at
        # w*seq_len, so consecutive windows share one boundary token —
        # every token is trained on exactly once per epoch
        self._per_shard = [max(0, (len(s) - 1) // self.seq_len)
                           for s in self._shards]
        self._cum = np.cumsum([0] + self._per_shard)
        self.n_windows = int(self._cum[-1])
        if self.n_windows == 0:
            raise ValueError(
                f"corpus too small: no shard holds seq_len+1="
                f"{self.seq_len + 1} tokens")
        self._perm_cache: dict[int, np.ndarray] = {}

    # ------------------------------------------------------------ reads --

    def window(self, idx: int) -> np.ndarray:
        """Window ``idx`` -> int32 [seq_len+1]."""
        s = int(np.searchsorted(self._cum, idx, side="right") - 1)
        off = (idx - self._cum[s]) * self.seq_len
        return np.asarray(
            self._shards[s][off:off + self.seq_len + 1], dtype=np.int32)

    def _perm(self, epoch: int) -> np.ndarray:
        """Epoch-seeded shuffle; tiny LRU since training only ever touches
        the current epoch (plus its neighbor at an epoch boundary)."""
        p = self._perm_cache.get(epoch)
        if p is None:
            p = np.random.default_rng(
                [self.seed, epoch]).permutation(self.n_windows)
            self._perm_cache[epoch] = p
            for k in sorted(self._perm_cache):
                if len(self._perm_cache) <= 4:
                    break
                del self._perm_cache[k]
        return p

    def window_ids_for_step(self, step: int, global_batch: int) -> np.ndarray:
        """The global window ids batch ``step`` consumes — the pure
        step->batch mapping the resume contract is built on."""
        first = step * global_batch
        idx = np.arange(first, first + global_batch)
        epochs = idx // self.n_windows
        pos = idx % self.n_windows
        return np.array([self._perm(int(e))[int(p)]
                         for e, p in zip(epochs, pos)])

    def state(self, step: int, global_batch: int) -> dict:
        """Observability: where step ``step`` sits in the epoch stream."""
        consumed = step * global_batch
        return {"epoch": consumed // self.n_windows,
                "position": consumed % self.n_windows,
                "seed": self.seed, "n_windows": self.n_windows}

    # ---------------------------------------------------------- batches --

    def batches(self, global_batch: int,
                start_step: int = 0,
                prefetch: int = 2) -> Iterator[dict]:
        """Infinite step-indexed batch stream: {"tokens": [local, S+1]}.

        Multi-host aware like ``synthetic_lm_batches``: each process yields
        its contiguous slice of the global batch. Pass this (wrapped in a
        lambda taking start_step) as ``loop.fit``'s ``batches`` callable —
        the preferred seekable form of the data-resume contract.

        ``prefetch`` batches are assembled AHEAD by a background producer
        thread (double-buffered: window gathers + np.stack overlap the
        train step instead of serializing with it — the VERDICT Missing #4
        gap between synthetic and file-backed MFU). ``prefetch=0`` is the
        old synchronous path. Ordering and SIGKILL-exact resume are
        untouched either way: batch ``i`` stays a pure function of
        ``(corpus, seq_len, global_batch, seed, i, process)`` — the thread
        only changes WHEN assembly happens, never WHAT step ``i`` yields.
        """
        import jax

        n_proc = jax.process_count()
        if global_batch % n_proc:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{n_proc} processes")
        local = global_batch // n_proc
        lo = jax.process_index() * local

        def assemble(step: int) -> dict:
            ids = self.window_ids_for_step(
                step, global_batch)[lo:lo + local]
            return {"tokens": np.stack(
                [self.window(int(i)) for i in ids])}

        if prefetch <= 0:
            step = start_step
            while True:
                yield assemble(step)
                step += 1

        import queue
        import threading

        q: "queue.Queue[tuple]" = queue.Queue(maxsize=int(prefetch))
        stop = threading.Event()

        def produce() -> None:
            step = start_step
            while not stop.is_set():
                try:
                    item = ("ok", assemble(step))
                except BaseException as e:  # propagate, don't die silently
                    item = ("err", e)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if item[0] == "err":
                    return
                step += 1

        t = threading.Thread(target=produce, daemon=True,
                             name="kft-dataset-prefetch")
        t.start()
        try:
            while True:
                kind, val = q.get()
                if kind == "err":
                    raise val
                yield val
        finally:
            stop.set()      # generator closed: release the producer
