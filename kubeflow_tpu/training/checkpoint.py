"""Checkpoint/resume via Orbax — first-class, because slice restart is the
normal failure mode at scale (SURVEY.md §5 checkpoint/resume: the reference
delegates this to user code; we own it).

Async checkpointing: the device->host copy happens at `save()`, serialization
runs in a background thread so the step loop keeps going.
"""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3, async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True, enable_async_checkpointing=async_save
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, force: bool = False):
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def restore(self, step: int | None = None, template: Any = None):
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None, None
        if template is not None:
            state = self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        else:
            state = self._mgr.restore(step)
        return step, state

    def latest_step(self):
        return self._mgr.latest_step()

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
