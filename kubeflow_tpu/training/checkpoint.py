"""Checkpoint/resume via Orbax — first-class, because slice restart is the
normal failure mode at scale (SURVEY.md §5 checkpoint/resume: the reference
delegates this to user code; we own it).

Async checkpointing: the device->host copy happens at `save()`, serialization
runs in a background thread so the step loop keeps going.

Remote durability (the "Orbax async checkpointing to GCS" spine, SURVEY.md
§5): two paths —

- ``directory`` may itself be a ``gs://`` bucket path: Orbax/TensorStore
  streams directly to GCS (needs cloud credentials; untestable in this
  environment, so it is passed through untouched).
- ``mirror=``: save locally (fast, node-local SSD), then a background
  worker replicates every *finished* step to the mirror URI and restore
  falls back to the mirror when the local directory is empty — the
  local-disk-lost recovery path. The default copier handles local/file://
  mirrors (in production that path is a mounted bucket, e.g. GCS FUSE);
  an injected ``copy_fn`` swaps in a real object-store client.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Any, Callable, Optional

import orbax.checkpoint as ocp

_REMOTE_SCHEMES = ("gs://", "s3://")


def _default_mirror_alarm(exc: Exception) -> None:
    """Operator contract (mirrors KFT_HEARTBEAT_FILE): pods get
    KFT_WARNING_FILE injected; appending a line raises a Warning condition
    on the owning job — how a degraded mirror becomes visible before the
    local disk it was guarding is actually needed."""
    path = os.environ.get("KFT_WARNING_FILE")
    if not path:
        return
    import json
    import time
    rec = {
        "ts": time.time(),
        "reason": "CheckpointMirrorDegraded",
        "message": f"{type(exc).__name__}: {exc}",
    }
    if path.startswith(("http://", "https://")):
        # KubeCluster transport: the shared heartbeat-POST helper (no
        # shared filesystem between pods and the operator)
        from kubeflow_tpu.training.loop import post_heartbeat

        post_heartbeat(path, warning=rec)
        return
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _is_remote(path: str) -> bool:
    return path.startswith(_REMOTE_SCHEMES)


def _strip_file_scheme(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, mirror: Optional[str] = None,
                 copy_fn: Optional[Callable[[str, str], None]] = None,
                 on_mirror_error: Optional[Callable[[Exception], None]]
                 = None):
        if _is_remote(directory):
            # bucket-direct: TensorStore owns the IO; no local mkdir
            self.directory = directory
        else:
            self.directory = os.path.abspath(_strip_file_scheme(directory))
            os.makedirs(self.directory, exist_ok=True)
        self.mirror = (_strip_file_scheme(mirror)
                       if mirror and not _is_remote(mirror) else mirror)
        self._copy = copy_fn or self._default_copy
        self.mirror_errors = 0          # background replication failures
        self.last_mirror_error: Optional[str] = None
        self._on_mirror_error = on_mirror_error or _default_mirror_alarm
        self._mirror_lock = threading.Lock()
        self._mirror_kick = threading.Event()
        self._mirror_stop = threading.Event()
        self._mirror_thread: Optional[threading.Thread] = None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True,
            enable_async_checkpointing=async_save,
        )
        if self.mirror is not None and not _is_remote(self.mirror):
            os.makedirs(self.mirror, exist_ok=True)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # ------------------------------------------------------------- save --

    def save(self, step: int, state: Any, force: bool = False):
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved and self.mirror is not None:
            self._kick_mirror()
        return saved

    def restore(self, step: int | None = None, template: Any = None):
        if self.mirror is not None and self._needs_mirror_fetch(step):
            self._fetch_from_mirror(step)
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None, None
        if template is not None:
            state = self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        else:
            state = self._mgr.restore(step)
        return step, state

    def latest_step(self):
        return self._mgr.latest_step()

    def wait(self):
        self._mgr.wait_until_finished()
        if self.mirror is not None:
            self._mirror_sync_guarded()

    def close(self):
        self._mirror_stop.set()
        self._mirror_kick.set()
        if self._mirror_thread is not None:
            self._mirror_thread.join(timeout=30)
        self._mgr.close()
        if self.mirror is not None:
            self._mirror_sync_guarded()

    # ----------------------------------------------------------- mirror --

    @staticmethod
    def _default_copy(src: str, dst: str) -> None:
        if _is_remote(dst):      # pragma: no cover - needs cloud creds
            raise NotImplementedError(
                f"no object-store client in this environment for {dst!r}; "
                "pass copy_fn= (or mount the bucket and use its path)")
        tmp = dst + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        shutil.copytree(src, tmp)
        os.replace(tmp, dst)

    def _kick_mirror(self) -> None:
        if self._mirror_thread is None:
            self._mirror_thread = threading.Thread(
                target=self._mirror_loop, daemon=True, name="ckpt-mirror")
            self._mirror_thread.start()
        self._mirror_kick.set()

    def _mirror_loop(self) -> None:
        while not self._mirror_stop.is_set():
            self._mirror_kick.wait()
            self._mirror_kick.clear()
            try:
                self._mgr.wait_until_finished()
                self.mirror_sync()
            except Exception as e:
                self._record_mirror_error(e)

    def _record_mirror_error(self, e: Exception) -> None:
        """The mirror must never kill the (possibly finished) step loop,
        but a dead mirror is exactly the failure to surface BEFORE the
        slice dies: count it and raise the alarm."""
        self.mirror_errors += 1
        self.last_mirror_error = f"{type(e).__name__}: {e}"
        try:
            self._on_mirror_error(e)
        except Exception:
            pass

    def _mirror_sync_guarded(self) -> None:
        try:
            self.mirror_sync()
        except Exception as e:
            self._record_mirror_error(e)

    def mirror_sync(self) -> list[int]:
        """Replicate every finished local step absent from the mirror.
        Idempotent; returns the steps copied this call."""
        if self.mirror is None or _is_remote(self.directory):
            return []
        copied = []
        with self._mirror_lock:
            for step in sorted(self._mgr.all_steps()):
                src = os.path.join(self.directory, str(step))
                dst = os.path.join(self.mirror, str(step))
                if not os.path.isdir(src) or os.path.exists(dst):
                    continue
                self._copy(src, dst)
                copied.append(step)
        return copied

    def _needs_mirror_fetch(self, want: Optional[int]) -> bool:
        """Restart-aware restore (elastic recovery): a replacement worker
        may land on a node whose local checkpoint dir is EMPTY (fresh
        standby) or STALE (the standby served an older incarnation of this
        job) — in both cases the durable mirror, not the local disk, holds
        the truth. Fetch when the local dir lacks the requested step, or —
        for latest-step restores — when the mirror is ahead of it."""
        local = self._mgr.latest_step()
        if local is None:
            return True
        if want is not None:
            return want not in self._mgr.all_steps()
        if _is_remote(self.mirror):
            return False
        try:
            newest = max((int(d) for d in os.listdir(self.mirror)
                          if d.isdigit() and os.path.isdir(
                              os.path.join(self.mirror, d))), default=None)
        except OSError:
            return False
        return newest is not None and newest > local

    def _fetch_from_mirror(self, want: Optional[int] = None) -> Optional[int]:
        """Local directory empty (node replaced / disk lost): pull the
        requested step (or the newest) back so restore proceeds normally."""
        if self.mirror is None or _is_remote(self.mirror):
            return None
        steps = [int(d) for d in os.listdir(self.mirror)
                 if d.isdigit() and os.path.isdir(
                     os.path.join(self.mirror, d))]
        if not steps:
            return None
        if want is not None and want not in steps:
            return None
        step = want if want is not None else max(steps)
        dst = os.path.join(self.directory, str(step))
        if not os.path.exists(dst):
            self._copy(os.path.join(self.mirror, str(step)), dst)
        self._mgr.reload()
        return step
