"""High-level training driver: checkpoint auto-resume, metrics export,
heartbeats, profiler toggle.

This is the recovery path SURVEY.md §5 makes first-class: slice restart is
the NORMAL failure mode at scale, so every run is structured as
restore-latest -> train -> periodic async save, and a restarted job resumes
where it left off with no operator involvement beyond re-running the pod.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
from typing import Any, Callable, Iterable, Optional

import jax

from kubeflow_tpu.training.checkpoint import CheckpointManager
from kubeflow_tpu.training.metrics import MetricsWriter
from kubeflow_tpu.training.trainer import Trainer


@dataclasses.dataclass
class FitResult:
    final_step: int
    resumed_from: Optional[int]
    last_metrics: dict


class Heartbeat:
    """Liveness file: mtime is the signal, content is the last step. The
    controller-side FileHeartbeatTracker reads these (SURVEY.md §2.8 fault
    signaling: heartbeat loss => job-level restart)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat(self, step: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, self.path)


def fit(
    trainer: Trainer,
    batches: Iterable[Any] | Callable[[int], Iterable[Any]],
    *,
    rng: jax.Array,
    max_steps: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    checkpoint_mirror: Optional[str] = None,
    metrics: Optional[MetricsWriter] = None,
    metrics_every: int = 10,
    heartbeat: Optional[Heartbeat] = None,
    profile_dir: Optional[str] = None,
    profile_steps: tuple[int, int] = (10, 20),
    on_step: Optional[Callable[[int, dict], None]] = None,
) -> FitResult:
    """Run training with auto-resume.

    If ``checkpoint_dir`` holds a checkpoint, state is restored and training
    continues from the saved step. Data is resumed deterministically:
    ``batches`` may be a callable ``(start_step) -> iterator`` (preferred —
    a step-indexed dataset can seek directly), or a plain iterable, in which
    case the first ``resumed_from`` batches are consumed and discarded so a
    restarted job sees the same step->batch mapping as an uninterrupted one.
    """
    # operator contract: pods get KFT_HEARTBEAT_FILE injected; beating it
    # per step is what feeds fault detection and the submit->first-step
    # latency metric without any explicit wiring in user code
    if heartbeat is None and os.environ.get("KFT_HEARTBEAT_FILE"):
        heartbeat = Heartbeat(os.environ["KFT_HEARTBEAT_FILE"])

    trainer.init_state(rng)
    resumed_from = None
    mgr = None
    if checkpoint_dir:
        mgr = CheckpointManager(
            checkpoint_dir,
            mirror=checkpoint_mirror
            or os.environ.get("KFT_CHECKPOINT_MIRROR") or None)
        latest = mgr.latest_step()
        if latest is not None:
            template = {"params": trainer.params,
                        "opt_state": trainer.opt_state}
            _, state = mgr.restore(latest, template=template)
            # re-place on the template's shardings: orbax can hand back
            # scalar/replicated leaves on a single device, which would then
            # clash with the mesh-placed params inside the jitted step
            state = jax.tree_util.tree_map(
                lambda x, t: jax.device_put(x, t.sharding)
                if hasattr(t, "sharding") else x,
                state, template,
            )
            trainer.params = state["params"]
            trainer.opt_state = state["opt_state"]
            trainer.step = latest
            resumed_from = latest

    if callable(batches):
        batches = batches(trainer.step)
    elif resumed_from:
        batches = itertools.islice(iter(batches), resumed_from, None)

    profiling = False
    last = {}
    for batch in batches:
        if trainer.step >= max_steps:
            break
        step = trainer.step

        if profile_dir and not profiling and step == profile_steps[0]:
            jax.profiler.start_trace(profile_dir)
            profiling = True
        m = trainer.train_step(batch)
        if profiling and trainer.step >= profile_steps[1]:
            # device_get, not block_until_ready: the latter is a no-op on
            # the remote-tunnel TPU platform and would close the trace
            # before the profiled steps actually execute
            float(jax.device_get(m["loss"]))
            jax.profiler.stop_trace()
            profiling = False

        last = {k: float(v) for k, v in m.items()
                if hasattr(v, "__float__")}
        if mgr is not None and mgr.mirror_errors:
            last["ckpt_mirror_errors"] = float(mgr.mirror_errors)
        if metrics is not None and trainer.step % metrics_every == 0:
            metrics.write(trainer.step, **last)
        if heartbeat is not None:
            heartbeat.beat(trainer.step)
        if mgr is not None and trainer.step % checkpoint_every == 0:
            mgr.save(trainer.step,
                     {"params": trainer.params,
                      "opt_state": trainer.opt_state})
        if on_step is not None:
            on_step(trainer.step, last)

    if profiling:
        jax.profiler.stop_trace()
    if mgr is not None:
        # final save — unless this exact step is already on disk (the
        # in-loop save fired on it, or a resumed run trained 0 steps);
        # force= bypasses the save-interval policy, not step collisions.
        if mgr.latest_step() != trainer.step:
            mgr.save(trainer.step,
                     {"params": trainer.params,
                      "opt_state": trainer.opt_state},
                     force=True)
        mgr.wait()
        mgr.close()
    if metrics is not None and last:
        metrics.write(trainer.step, **last)
    return FitResult(final_step=trainer.step, resumed_from=resumed_from,
                     last_metrics=last)
