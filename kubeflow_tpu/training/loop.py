"""High-level training driver: checkpoint auto-resume, metrics export,
heartbeats, profiler toggle.

This is the recovery path SURVEY.md §5 makes first-class: slice restart is
the NORMAL failure mode at scale, so every run is structured as
restore-latest -> train -> periodic async save, and a restarted job resumes
where it left off with no operator involvement beyond re-running the pod.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
from typing import Any, Callable, Iterable, Optional

import jax

from kubeflow_tpu.training.checkpoint import CheckpointManager
from kubeflow_tpu.training.metrics import MetricsWriter
from kubeflow_tpu.training.trainer import Trainer


@dataclasses.dataclass
class FitResult:
    final_step: int
    resumed_from: Optional[int]
    last_metrics: dict
    # set iff the jax.profiler window actually ran: {"dir", "t_start",
    # "t_stop"} wall times of start_trace/stop_trace — what worker_check
    # stamps into the phase report (a run that never reached the window
    # must not report a phantom profile artifact)
    profile: Optional[dict] = None


def post_heartbeat(url: str, step=None, warning=None, spans=None,
                   timeout: float = 5.0) -> bool:
    """ONE http transport for the heartbeat contract (beats + warnings +
    worker-reported spans; loop.Heartbeat, checkpoint's mirror alarm and
    the MPMD stage workers all route through here — the operator folds
    ``spans`` into the /apis/v1/trace job trace). Failures are
    swallowed: missed beats ARE the failure signal."""
    import json
    import urllib.request

    body: dict = {}
    if step is not None:
        body["step"] = int(step)
    if warning is not None:
        body["warning"] = warning
    if spans:
        # span dicts (obs/trace.Span.to_dict form); the operator
        # validates field-by-field and bounds per pod
        body["spans"] = list(spans)
    try:
        req = urllib.request.Request(
            url, method="POST", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=timeout).close()
        return True
    except Exception:
        return False


class Heartbeat:
    """Liveness signal: the controller-side FileHeartbeatTracker turns
    missed beats into gang restarts (SURVEY.md §2.8 fault signaling).

    Two transports behind ONE env value (KFT_HEARTBEAT_FILE):
    - a filesystem path (LocalProcessCluster: shared fs) — mtime is the
      signal, content is the last step;
    - an http(s) URL (KubeCluster: pods and operator share no
      filesystem) — beats POST to the operator's heartbeat route, which
      writes the same tracker file on ITS side, so every downstream
      consumer (staleness sweep, first-step metric, warning sweep) is
      transport-agnostic. URL beats post from a BACKGROUND thread
      holding only the latest step (rate-limited), so a slow or down
      operator can never stall the training hot loop.
    """

    def __init__(self, path: str, min_interval_s: float = 1.0):
        self.path = path
        self.is_url = path.startswith(("http://", "https://"))
        self.min_interval_s = min_interval_s
        if self.is_url:
            import queue
            import threading

            self._latest: Optional[int] = None
            self._latest_lock = threading.Lock()
            self._warnings: "queue.Queue[dict]" = queue.Queue()
            self._kick = threading.Event()
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._pump, daemon=True, name="kft-heartbeat-post")
            self._thread.start()
        else:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat(self, step: int, warning: Optional[dict] = None) -> None:
        if self.is_url:
            with self._latest_lock:
                self._latest = int(step)
            if warning is not None:
                self._warnings.put(warning)
            self._kick.set()
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, self.path)

    def _take(self) -> tuple[Optional[int], Optional[dict]]:
        """Atomically claim the pending step (the lock closes the race
        where a beat lands between the read and the reset) + one warning."""
        import queue

        with self._latest_lock:
            step, self._latest = self._latest, None
        try:
            warning = self._warnings.get_nowait()
        except queue.Empty:
            warning = None
        return step, warning

    def _pump(self) -> None:
        while not self._stop.is_set():
            self._kick.wait()
            self._kick.clear()
            step, warning = self._take()
            if step is not None or warning is not None:
                post_heartbeat(self.path, step=step, warning=warning)
            if not self._warnings.empty() or self._latest is not None:
                self._kick.set()       # drain remaining work next loop
            self._stop.wait(self.min_interval_s)   # rate limit

    def close(self) -> None:
        if self.is_url:
            self._stop.set()
            self._kick.set()
            self._thread.join(timeout=10.0)
            # final flush: the last pre-shutdown beat/warnings must not be
            # lost in the pump — post whatever remains, synchronously
            while True:
                step, warning = self._take()
                if step is None and warning is None:
                    break
                post_heartbeat(self.path, step=step, warning=warning)


def profile_from_env(env=None) -> tuple[Optional[str],
                                        Optional[tuple[int, int]]]:
    """The pod env contract for the jax.profiler toggle:
    KFT_PROFILE_DIR names the trace output directory (unset = profiling
    off) and KFT_PROFILE_STEPS is "start:stop" (or "start,stop") step
    bounds for the profiled window. Returns (dir, steps) with None for
    whatever is unset/malformed — a bad value must never fail a job over
    an optional profile."""
    env = os.environ if env is None else env
    profile_dir = env.get("KFT_PROFILE_DIR") or None
    steps = None
    raw = env.get("KFT_PROFILE_STEPS") or ""
    if raw:
        try:
            a, b = raw.replace(",", ":").split(":")
            steps = (int(a), int(b))
            if steps[0] >= steps[1] or steps[0] < 0:
                steps = None
        except ValueError:
            steps = None
    return profile_dir, steps


def restore_latest(trainer: Trainer, mgr: CheckpointManager):
    """Restore the newest checkpoint into ``trainer`` (params/opt_state
    re-placed on the template's shardings, step advanced). Returns the
    restored step or None when no checkpoint exists. Shared by ``fit``
    and by replacement workers that must restore BEFORE loading the
    compiled executable (the elastic-recovery takeover order).

    The restored state is laundered through a jitted identity so every
    buffer is a fresh XLA-runtime allocation. Load-bearing for elastic
    recovery, not a style choice: restore/device_put hand back arrays
    whose storage the runtime treats as EXTERNAL, and a DESERIALIZED
    train step (the executable-depot hit a replacement worker takes)
    donates its inputs — donating an external buffer to a deserialized
    executable corrupts the heap (observed: NaN updates from the first
    donated call, "double free or corruption", SIGSEGV/SIGABRT; a
    locally jit-compiled step tolerates the same inputs). One extra
    device-side copy per restore buys a state every executable kind can
    safely consume."""
    latest = mgr.latest_step()
    if latest is None:
        return None
    template = {"params": trainer.params,
                "opt_state": trainer.opt_state}
    _, state = mgr.restore(latest, template=template)
    # re-place on the template's shardings: orbax can hand back
    # scalar/replicated/host leaves, which would otherwise clash with
    # the mesh-placed params inside the jitted step
    state = jax.tree_util.tree_map(
        lambda x, t: jax.device_put(x, t.sharding)
        if hasattr(t, "sharding") else x,
        state, template,
    )
    state = jax.jit(lambda s: s)(state)     # the buffer launder (above)
    trainer.params = state["params"]
    trainer.opt_state = state["opt_state"]
    trainer.step = latest
    return latest


def fit(
    trainer: Trainer,
    batches: Iterable[Any] | Callable[[int], Iterable[Any]],
    *,
    rng: jax.Array,
    max_steps: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    checkpoint_mirror: Optional[str] = None,
    metrics: Optional[MetricsWriter] = None,
    metrics_every: int = 10,
    heartbeat: Optional[Heartbeat] = None,
    profile_dir: Optional[str] = None,
    profile_steps: Optional[tuple[int, int]] = None,
    on_step: Optional[Callable[[int, dict], None]] = None,
    already_resumed: Optional[int] = None,
) -> FitResult:
    """Run training with auto-resume.

    If ``checkpoint_dir`` holds a checkpoint, state is restored and training
    continues from the saved step. Data is resumed deterministically:
    ``batches`` may be a callable ``(start_step) -> iterator`` (preferred —
    a step-indexed dataset can seek directly), or a plain iterable, in which
    case the first ``resumed_from`` batches are consumed and discarded so a
    restarted job sees the same step->batch mapping as an uninterrupted one.

    ``already_resumed`` says the caller restored the checkpoint itself
    (a replacement worker restores BEFORE loading the depot executable);
    fit then skips its own restore but still performs the resume
    handshake: an immediate heartbeat at the takeover step, so the
    operator's staleness sweep sees the new incarnation live BEFORE the
    (possibly long) first post-resume step completes.
    """
    # operator contract: pods get KFT_HEARTBEAT_FILE injected; beating it
    # per step is what feeds fault detection and the submit->first-step
    # latency metric without any explicit wiring in user code
    if heartbeat is None and os.environ.get("KFT_HEARTBEAT_FILE"):
        heartbeat = Heartbeat(os.environ["KFT_HEARTBEAT_FILE"])
    # profiler toggle rides the pod env the same way (KFT_PROFILE_DIR /
    # KFT_PROFILE_STEPS): explicit arguments win, env fills the gaps
    env_dir, env_steps = profile_from_env()
    if profile_dir is None:
        profile_dir = env_dir
    if profile_steps is None:
        profile_steps = env_steps or (10, 20)

    # a caller that already initialized (e.g. worker_check's precompile
    # phase, which needs live state to lower the step) keeps its state —
    # re-running init here would both waste a full param/opt init and
    # land it inside the phase the bench attributes to step 1
    if trainer.params is None:
        trainer.init_state(rng)
    resumed_from = already_resumed
    mgr = None
    if checkpoint_dir:
        mgr = CheckpointManager(
            checkpoint_dir,
            mirror=checkpoint_mirror
            or os.environ.get("KFT_CHECKPOINT_MIRROR") or None)
        # a caller that already restored (``already_resumed`` — e.g. a
        # replacement worker that must restore before loading the depot
        # executable) keeps its state; restoring again here would both
        # waste the IO and reorder it after the executable load
        if already_resumed is None:
            resumed_from = restore_latest(trainer, mgr)
    if resumed_from is not None and heartbeat is not None:
        # resume handshake: confirm liveness + the exact takeover step
        # to the operator NOW — the replacement's first beat must not
        # wait out the first post-resume step (covers BOTH the
        # fit-restored and the caller-pre-restored paths)
        heartbeat.beat(resumed_from)

    if callable(batches):
        batches = batches(trainer.step)
    elif resumed_from:
        batches = itertools.islice(iter(batches), resumed_from, None)

    profiling = False
    profile_info: Optional[dict] = None
    last = {}
    for batch in batches:
        if trainer.step >= max_steps:
            break
        step = trainer.step

        if profile_dir and not profiling and step == profile_steps[0]:
            jax.profiler.start_trace(profile_dir)
            profiling = True
            profile_info = {"dir": profile_dir, "t_start": time.time()}
        m = trainer.train_step(batch)
        if profiling and trainer.step >= profile_steps[1]:
            # device_get, not block_until_ready: the latter is a no-op on
            # the remote-tunnel TPU platform and would close the trace
            # before the profiled steps actually execute
            float(jax.device_get(m["loss"]))
            jax.profiler.stop_trace()
            profiling = False
            profile_info["t_stop"] = time.time()

        last = {k: float(v) for k, v in m.items()
                if hasattr(v, "__float__")}
        if mgr is not None and mgr.mirror_errors:
            last["ckpt_mirror_errors"] = float(mgr.mirror_errors)
        if metrics is not None and trainer.step % metrics_every == 0:
            metrics.write(trainer.step, **last)
        if heartbeat is not None:
            heartbeat.beat(trainer.step)
        if mgr is not None and trainer.step % checkpoint_every == 0:
            mgr.save(trainer.step,
                     {"params": trainer.params,
                      "opt_state": trainer.opt_state})
        if on_step is not None:
            on_step(trainer.step, last)

    if profiling:
        jax.profiler.stop_trace()
        profile_info["t_stop"] = time.time()
    if mgr is not None:
        # final save — unless this exact step is already on disk (the
        # in-loop save fired on it, or a resumed run trained 0 steps);
        # force= bypasses the save-interval policy, not step collisions.
        if mgr.latest_step() != trainer.step:
            mgr.save(trainer.step,
                     {"params": trainer.params,
                      "opt_state": trainer.opt_state},
                     force=True)
        mgr.wait()
        mgr.close()
    if metrics is not None and last:
        metrics.write(trainer.step, **last)
    return FitResult(final_step=trainer.step, resumed_from=resumed_from,
                     last_metrics=last,
                     profile=(profile_info
                              if profile_info and "t_stop" in profile_info
                              else None))
