"""The operator process — the reference's defining artifact, TPU-native.

Parity: SURVEY.md §2.1 'Operator entrypoint' ([U] training-operator:
cmd/training-operator.v1/main.go) — a long-running daemon that (a)
continuously reconciles every registered job, (b) sweeps worker heartbeats
(fault signaling, §2.8), (c) ticks serving reconcilers/autoscalers, and
(d) serves /healthz + /metrics plus a small REST API surface (the
kube-apiserver role in this single-binary architecture: job submission is
an HTTP POST of the JobSpec YAML/JSON).

North-star #2 (BASELINE.md "job-submit -> first-training-step latency") is
measured here: the operator injects KFT_HEARTBEAT_FILE into every pod; the
training loop auto-beats it each step (content = step number), and the
heartbeat sweep records the delta between submit time and the first beat
with step >= 1 as ``kft_submit_to_first_step_seconds``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubeflow_tpu.api.types import Condition, ConditionType, from_yaml, to_yaml
from kubeflow_tpu.controller.heartbeat import FileHeartbeatTracker, check_heartbeats
from kubeflow_tpu.controller.reconciler import JobController
from kubeflow_tpu.obs import expo as obs_expo
from kubeflow_tpu.obs import export as obs_export
from kubeflow_tpu.obs.histogram import Histogram
from kubeflow_tpu.parallel.depot import (
    DEPOT_REPLACE_HEADER, DEPOT_TOKEN_HEADER,
)


class Metrics:
    """Minimal Prometheus-style registry (counters + gauges + histograms),
    rendered through the ONE shared exposition helper (obs/expo.py) the
    model server also uses — # HELP/# TYPE per family, counter names
    enforced to the _total/_sum/_count convention at render time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Histogram] = {}

    @staticmethod
    def _key(name: str, labels: Optional[dict] = None) -> str:
        if not labels:
            return name
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}}"

    def inc(self, name: str, labels: Optional[dict] = None, by: float = 1.0):
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + by

    def set(self, name: str, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, value: float,
                labels: Optional[dict] = None):
        """Record into a histogram family (created on first use). Family
        names must end in _seconds (the timing convention the exposition
        helper enforces)."""
        key = self._key(name, labels)
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = Histogram()
        hist.observe(value)

    def get(self, name: str, labels: Optional[dict] = None) -> Optional[float]:
        key = self._key(name, labels)
        with self._lock:
            return self._counters.get(key, self._gauges.get(key))

    @staticmethod
    def _split(key: str) -> tuple[str, Optional[str]]:
        bare, _, rest = key.partition("{")
        return bare, (rest[:-1] if rest else None)

    def render(self) -> str:
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        families: dict[tuple, list] = {}
        for items, mtype in ((counters, "counter"), (gauges, "gauge"),
                             (hists, "histogram")):
            for k, v in items:
                bare, labels = self._split(k)
                families.setdefault((bare, mtype), []).append((labels, v))
        return obs_expo.render_exposition(
            [(name, mtype, samples)
             for (name, mtype), samples in families.items()])


class Operator:
    """Reconcile loops + heartbeat sweep + serving ticks, as daemon threads.

    ``serving_tickers`` is a list of zero-arg callables (e.g. a closure over
    ServingController.reconcile or Autoscaler.tick) invoked every
    ``serving_period`` — the knative/HPA control-loop role."""

    def __init__(
        self,
        controller: JobController,
        heartbeat_dir: Optional[str] = None,
        heartbeat_timeout_s: float = 60.0,
        startup_grace_s: float = 300.0,
        reconcile_period: float = 0.25,
        heartbeat_period: float = 1.0,
        reconcile_slow_period: float = 5.0,
        informer_resync_s: float = 30.0,
        serving_tickers: tuple = (),
        serving_period: float = 1.0,
        experiment_manager=None,
        serving_ticker=None,
        auth=None,
        dashboard=None,
        webui=None,
        advertise_url: Optional[str] = None,
        pipeline_client=None,
        warm_pool=None,
        depot=None,
    ):
        self.controller = controller
        # One lock serializes every compound mutation of controller state
        # (submit / delete / reconcile / heartbeat sweep / tickers): the
        # loops and the HTTP threads otherwise interleave read-modify-write
        # sequences. Contention is negligible at these loop periods.
        self._lock = threading.RLock()
        # one daemon, every control loop (SURVEY.md §7 single-binary stance):
        # the HPO experiment manager and the serving reconcile+autoscale
        # ticker run on the serving period alongside any custom tickers
        self.experiments = experiment_manager
        self.serving = serving_ticker
        serving_tickers = tuple(serving_tickers)
        # the experiment ticker mutates JobController/cluster state (trial
        # jobs, pods), so it runs under the operator lock; the serving
        # ticker takes the SAME lock internally but only around mutations —
        # its concurrency probe does blocking HTTP and must not hold it
        if experiment_manager is not None:
            serving_tickers += (
                lambda: self._locked(experiment_manager.tick),)
            # trial-swarm wiring (hpo/swarm.py): the manager's swarm
            # runners post trial spans through heartbeat_post and push
            # kft_swarm_* metrics into this operator's registry
            if getattr(experiment_manager, "swarm_pool", None) is not None:
                experiment_manager.operator = self
        if serving_ticker is not None:
            serving_ticker.lock = self._lock
            serving_tickers += (serving_ticker.tick,)
        # optional platform.auth.Auth: bearer-token authn + KFAM authz on
        # every namespaced route (the istio/dex L1 role); None = open
        self.auth = auth
        if getattr(auth, "profiles", None) is not None:
            # quota admission registers on the CONTROLLER so every
            # submission path (HTTP, SDK, HPO trial jobs) is metered
            controller.admission_checks.append(self._check_quota)
        # optional platform.dashboard.Dashboard: served at /dashboard
        # (HTML) and /apis/v1/dashboard (JSON), user-scoped when auth is on
        self.dashboard = dashboard
        # optional platform.webui.WebUI: the browser surface at /ui/*,
        # sharing the operator lock for its CRUD mutations
        self.webui = webui
        if webui is not None and webui._lock is None:
            webui._lock = self._lock
        # optional pipelines.PipelineClient: the ml-pipeline API-server
        # role (upload IR, create/list runs, recurring schedules).
        # Pipelines are platform-scoped (not namespaced) like the
        # reference's shared pipeline store; PipelineClient self-locks.
        self.pipelines = pipeline_client
        # data-plane ingress (istio gateway role): /serving/{ns}/{name}/...
        # proxied to a traffic-split-chosen predictor pod
        self.ingress = None
        if serving_ticker is not None:
            from kubeflow_tpu.serving.ingress import IngressGateway

            self.ingress = IngressGateway(
                serving_ticker.controller,
                autoscaler=serving_ticker.autoscaler)
        self.metrics = Metrics()
        self.heartbeat_dir = heartbeat_dir
        self.tracker = (
            FileHeartbeatTracker(heartbeat_dir, timeout_s=heartbeat_timeout_s,
                                 startup_grace_s=startup_grace_s)
            if heartbeat_dir else None
        )
        self.reconcile_period = reconcile_period
        self.heartbeat_period = heartbeat_period
        # informer mode (kube backend): reconcile wakes on pod events and
        # otherwise idles at the slow period — no 0.25s LIST storm against
        # a real apiserver (the client-go informer architecture)
        self.reconcile_slow_period = reconcile_slow_period
        self.informer_resync_s = informer_resync_s
        self._pod_event_wake: Optional[threading.Event] = None
        # executable depot (parallel/depot.py): compile-once-per-gang.
        # The operator is the depot's home — it stores entries (under the
        # heartbeat dir by default), serves them over the SAME HTTP
        # transport heartbeats ride (token-fenced: a depot entry is a
        # pickled executable, loading one is code execution), and injects
        # the worker env contract via the pod mutator below. Workers
        # report their hit/fallback counters over the phases POST; both
        # sides surface as kft_depot_* /metrics.
        if depot is None and heartbeat_dir:
            from kubeflow_tpu.parallel.depot import DirectoryDepot

            depot = DirectoryDepot(os.path.join(heartbeat_dir, "depot"))
        self.depot = depot
        import uuid

        self.depot_token = uuid.uuid4().hex
        # worker-reported depot counters, delta-tracked per pod so the
        # at-least-once phases transport can re-post without double counts
        self._depot_reported: dict[tuple[str, str, str, str], dict] = {}
        # warm-pool subsystem (controller/warmpool.py): the operator owns
        # the replenish tick and exports the pool counters; the cluster's
        # start_pod consults the pool at admission
        self.warm_pool = warm_pool
        if warm_pool is not None:
            if getattr(controller.cluster, "warm_pool", None) is None:
                controller.cluster.warm_pool = warm_pool
            serving_tickers += (self._tick_warm_pool,)
        self.serving_tickers = tuple(serving_tickers)
        self.serving_period = serving_period
        self._submit_times: dict[tuple[str, str], float] = {}
        self._first_step_seen: set[tuple[str, str]] = set()
        self._warn_offsets: dict[str, int] = {}     # warn file -> read pos
        # worker-reported phase timestamps delivered over the heartbeat
        # transport ((ns, job, uid, pod) -> {phase: unix_ts}); the
        # kube-backend replacement for reading KFT_PHASES_PATH files off a
        # shared fs. uid-scoped like the warning files: a resubmitted
        # same-name job must not inherit a dead incarnation's stamps.
        self.phase_reports: dict[tuple[str, str, str, str], dict] = {}
        # worker-POSTed explicit spans (same heartbeat transport, key
        # "spans"): merged with the phase-derived spans + the reconciler
        # recovery log into the /apis/v1/trace/{ns}/{job} job trace
        self.span_reports: dict[tuple[str, str, str, str], list] = {}
        # heartbeat transport for pods that share no filesystem with this
        # daemon (KubeCluster): inject an http URL instead of a file path;
        # the POST handler writes the SAME tracker files locally, keeping
        # every downstream consumer transport-agnostic. In-cluster installs
        # pass the operator Service DNS; local dev defaults to the bound
        # address at start().
        self.advertise_url = advertise_url
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None

        if self.tracker is not None:
            # chain onto any existing mutator: every pod gets its heartbeat
            # file path so the training loop can auto-beat it
            prev = controller.pod_mutator

            def mutator(pod):
                if prev is not None:
                    pod = prev(pod)
                job = pod.labels.get("job-name", "")
                if self._pods_share_fs():
                    pod.env.setdefault(
                        "KFT_HEARTBEAT_FILE",
                        self.tracker.path_for(job, pod.name))
                    pod.env.setdefault(
                        "KFT_WARNING_FILE",
                        self._warning_path(job, pod.name,
                                           pod.labels.get("job-uid", "")))
                    if getattr(self.depot, "path", None):
                        # shared fs: workers read/publish the depot
                        # directory itself — no HTTP round trip
                        pod.env.setdefault("KFT_DEPOT", self.depot.path)
                    if self.advertise_url:
                        # phase stamps still POST over HTTP even on a
                        # shared fs: phase_reports (and the job trace
                        # built from them at /apis/v1/trace) must not be
                        # kube-backend-only
                        pod.env.setdefault(
                            "KFT_PHASES_PATH",
                            f"{self.advertise_url.rstrip('/')}/apis/v1/"
                            f"namespaces/{pod.namespace}/jobs/{job}/pods/"
                            f"{pod.name}/heartbeat"
                            f"?uid={pod.labels.get('job-uid', '')}")
                elif self.advertise_url:
                    # uid-scoped like the file transport: a zombie pod of
                    # a dead incarnation must not feed the new job
                    url = (f"{self.advertise_url.rstrip('/')}/apis/v1/"
                           f"namespaces/{pod.namespace}/jobs/{job}/pods/"
                           f"{pod.name}/heartbeat"
                           f"?uid={pod.labels.get('job-uid', '')}")
                    pod.env.setdefault("KFT_HEARTBEAT_FILE", url)
                    pod.env.setdefault("KFT_WARNING_FILE", url)
                    # phase timestamps ride the SAME transport: workers on
                    # other nodes cannot write local files this daemon
                    # reads, so the submit→first-step decomposition POSTs
                    # here too (heartbeat_post -> phase_reports)
                    pod.env.setdefault("KFT_PHASES_PATH", url)
                    if self.depot is not None:
                        pod.env.setdefault(
                            "KFT_DEPOT",
                            f"{self.advertise_url.rstrip('/')}"
                            "/apis/v1/depot")
                        pod.env.setdefault(
                            "KFT_DEPOT_TOKEN", self.depot_token)
                        # node-local cache, shared across pods on a node
                        # (entries are content-addressed): the claim-time
                        # pre-fetch and worker write-through both land
                        # here — without a default, the warm pool's
                        # pre-fetch would be inert on every deployment
                        # that doesn't hand-set a cache dir
                        pod.env.setdefault(
                            "KFT_DEPOT_CACHE", "/tmp/kft-depot-cache")
                return pod

            controller.pod_mutator = mutator

    # ---------------- job API (the apiserver role) ----------------

    def _locked(self, fn):
        with self._lock:
            return fn()

    @staticmethod
    def _job_chips(job) -> int:
        return sum(
            spec.replicas * spec.template.tpu.chips_per_host
            for spec in job.replica_specs.values()
            if spec.template.tpu is not None)

    def _check_quota(self, job) -> None:
        """Profile ResourceQuota admission (the quota-webhook role): TPU
        chips + job count per namespace, enforced before the job exists."""
        profiles = getattr(self.auth, "profiles", None)
        if profiles is None:
            return
        used_chips = jobs_running = 0
        for (ns, _), other in self.controller.jobs.items():
            if ns != job.namespace or other.status.is_finished():
                continue
            jobs_running += 1
            used_chips += self._job_chips(other)
        profiles.check_quota(
            job.namespace, tpu_chips=used_chips, jobs_running=jobs_running,
            new_jobs=1, new_tpu_chips=self._job_chips(job))

    def submit(self, job) -> None:
        with self._lock:
            self.controller.submit(job)
            self._submit_times[(job.namespace, job.name)] = time.time()
        self.metrics.inc("kft_jobs_submitted_total")
        if self._pod_event_wake is not None:
            self._pod_event_wake.set()       # reconcile now, not next tick

    def delete(self, ns: str, name: str) -> None:
        with self._lock:
            self.controller.delete(ns, name)
            # drop the dead incarnation's phase stamps with it (bounded
            # memory; a resubmission records fresh ones under its new uid)
            for key in [k for k in self.phase_reports
                        if k[0] == ns and k[1] == name]:
                self.phase_reports.pop(key, None)
            for key in [k for k in self.span_reports
                        if k[0] == ns and k[1] == name]:
                self.span_reports.pop(key, None)
            for key in [k for k in self._depot_reported
                        if k[0] == ns and k[1] == name]:
                self._depot_reported.pop(key, None)
        if self._pod_event_wake is not None:
            self._pod_event_wake.set()

    # ---------------- loops ----------------

    def _wait_reconcile(self) -> bool:
        """Block until the next reconcile pass is due; True = stopping.
        Poll-driven on in-memory/local backends; on an informer backend,
        wake immediately on any pod event and otherwise idle at the slow
        period (job-level timers — active deadlines, restart backoff —
        still get evaluated each slow tick)."""
        if self._pod_event_wake is None:
            return self._stop.wait(self.reconcile_period)
        if self._pod_event_wake.wait(timeout=self.reconcile_slow_period):
            self._pod_event_wake.clear()
        return self._stop.is_set()

    def _reconcile_loop(self):
        while not self._wait_reconcile():
            keys = list(self.controller.jobs.keys())
            self.metrics.set("kft_jobs_registered", len(keys))
            pending = 0
            phases: dict[str, int] = {}
            for ns, name in keys:
                t0 = time.perf_counter()
                try:
                    with self._lock:
                        job = self.controller.reconcile(ns, name)
                except Exception:
                    self.metrics.inc("kft_reconcile_errors_total")
                    continue
                dt = time.perf_counter() - t0
                self.metrics.inc("kft_reconcile_total")
                self.metrics.inc("kft_reconcile_seconds_sum", by=dt)
                if job is None:
                    continue
                cond = job.status.condition()
                phases[cond.value if cond else "Unknown"] = (
                    phases.get(cond.value if cond else "Unknown", 0) + 1)
                if cond is not None and cond.value == "Created":
                    pending += 1
            for phase, n in phases.items():
                self.metrics.set("kft_jobs", n, {"phase": phase})
            # elastic-recovery counters (reconciler-side): exported as
            # real Prometheus counters via deltas, like the warm pool's
            last = getattr(self, "_recovery_exported", {})
            for k in ("worker_replacements_total", "gang_restarts_total"):
                cur = self.controller.metrics.get(k, 0)
                if cur > last.get(k, 0):
                    self.metrics.inc(f"kft_{k}", by=cur - last.get(k, 0))
                last[k] = cur
            self._recovery_exported = last
            self.metrics.set(
                "kft_restart_backoff_seconds",
                self.controller.metrics.get("restart_backoff_seconds", 0.0))
            self.metrics.set(
                "kft_gang_queue_depth",
                # snapshot: submit/forget churn (e.g. a trial swarm)
                # mutates groups from other threads mid-iteration
                sum(1 for g in list(
                        getattr(self.controller.scheduler, "groups", {}))
                    if not self.controller.scheduler.is_admitted(*g))
                if hasattr(self.controller.scheduler, "groups") else pending,
            )

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_period):
            for (ns, name) in list(self.controller.jobs.keys()):
                with self._lock:
                    stale = check_heartbeats(
                        self.controller, ns, name, self.tracker)
                if stale:
                    self.metrics.inc("kft_heartbeat_stale_total", by=len(stale))
                self._record_first_step(ns, name)
                self._collect_warnings(ns, name)

    def _pods_share_fs(self) -> bool:
        """File heartbeat transport works only when worker pods and this
        daemon see one filesystem (in-memory/local-process backends).
        KubeCluster pods live on other nodes — they beat over HTTP."""
        from kubeflow_tpu.controller.kube import KubeCluster

        return not isinstance(self.controller.cluster, KubeCluster)

    def heartbeat_post(self, ns: str, job_name: str, pod_name: str,
                       body, uid: str = "") -> bool:
        """The HTTP heartbeat sink: write the same tracker/warning files
        the shared-fs transport writes, so staleness sweeps, the
        first-step metric, and the warning sweep need no second code
        path. Returns False (dead-lettered) for an unknown job OR a uid
        that no longer matches — a zombie pod of a deleted incarnation
        must not feed the job that replaced it. Body is untrusted
        (unauthenticated route): anything malformed is rejected, never
        raised."""
        if self.tracker is None or not isinstance(body, dict):
            return False
        job = self.controller.get(ns, job_name)
        # uid is REQUIRED to match: injected heartbeat URLs always carry
        # ?uid=, so a beat without one is a forged/stale client — accepting
        # it would let a replaced incarnation's zombie feed this tracker
        if job is None or job.uid != uid:
            return False
        step = body.get("step")
        if step is not None:
            try:
                step = int(step)
            except (TypeError, ValueError):
                return False
            path = self.tracker.path_for(job_name, pod_name)
            # unique tmp per writer thread: concurrent beats must not
            # race each other's os.replace
            tmp = f"{path}.{threading.get_ident()}.tmp"
            try:
                with open(tmp, "w") as f:
                    f.write(str(step))
                os.replace(tmp, path)
            except OSError:
                return False
        warning = body.get("warning")
        if isinstance(warning, dict):
            with open(self._warning_path(job_name, pod_name, job.uid),
                      "a") as f:
                f.write(json.dumps(warning) + "\n")
        phases = body.get("phases")
        if isinstance(phases, dict):
            # submit→first-step decomposition over the wire (kube backend:
            # no shared fs). Merge — workers re-post the whole dict per
            # phase, and a lagging duplicate must not erase a later stamp.
            # Short strings ride too (artifact stamps like the profiler's
            # trace-dir path): they surface as job-trace span attrs, never
            # as timestamps.
            clean: dict = {}
            for k, v in phases.items():
                if isinstance(v, (int, float)):
                    clean[str(k)] = float(v)
                elif isinstance(v, str) and len(v) <= 512:
                    clean[str(k)] = v
            with self._lock:
                self.phase_reports.setdefault(
                    (ns, job_name, job.uid, pod_name), {}).update(clean)
        spans = body.get("spans")
        if isinstance(spans, list):
            # explicit worker spans over the same transport: validated
            # field-by-field (untrusted body) and bounded per pod
            clean_spans = []
            for s in spans[:64]:
                if not isinstance(s, dict):
                    continue
                try:
                    rec = {"name": str(s["name"])[:128],
                           "t0": float(s["t0"]), "t1": float(s["t1"])}
                except (KeyError, TypeError, ValueError):
                    continue
                if isinstance(s.get("attrs"), dict):
                    rec["attrs"] = {str(k)[:64]: v
                                    for k, v in s["attrs"].items()
                                    if isinstance(v, (int, float, str))}
                clean_spans.append(rec)
            if clean_spans:
                with self._lock:
                    store = self.span_reports.setdefault(
                        (ns, job_name, job.uid, pod_name), [])
                    store.extend(clean_spans)
                    del store[:-256]          # bounded per pod
        depot = body.get("depot")
        if isinstance(depot, dict):
            # worker-side depot counters (hits / deserialize_failures /
            # ...) folded into /metrics as kft_depot_worker_<k>_total —
            # namespaced apart from the server-side publish/fetch
            # counters. Workers post ABSOLUTE counts over an
            # at-least-once transport, so export per-pod deltas — a
            # re-post must not double count.
            clean = {str(k): int(v) for k, v in depot.items()
                     if isinstance(v, (int, float))}
            key = (ns, job_name, job.uid, pod_name)
            with self._lock:
                last = self._depot_reported.setdefault(key, {})
                for k, v in clean.items():
                    prev = last.get(k, 0)
                    # v < prev = the pod restarted and its counters
                    # reset (same name+uid): Prometheus counter-reset
                    # semantics — the new absolute IS the delta, not
                    # swallowed under the old high-water mark
                    delta = v if v < prev else v - prev
                    if delta > 0:
                        self.metrics.inc(
                            f"kft_depot_worker_{k}_total", by=delta)
                    last[k] = v
        return True

    # ---------------- executable depot (the depot-server role) ----------

    def depot_authorized(self, token: Optional[str]) -> bool:
        """Depot routes are worker-facing like heartbeats, but NOT open: a
        depot entry is a pickled executable, so reads and writes require
        the operator-injected KFT_DEPOT_TOKEN (the zygote-token trust
        model — possession implies pod-spec read rights)."""
        return self.depot is not None and token == self.depot_token

    def depot_fetch(self, key: str) -> Optional[bytes]:
        try:
            data = self.depot.get(key)
        except (OSError, ValueError):
            data = None
        self.metrics.inc("kft_depot_server_hits_total" if data is not None
                         else "kft_depot_server_misses_total")
        return data

    def depot_publish(self, key: str, data: bytes,
                      replace: bool = False) -> bool:
        """``replace``: the publisher fetched the existing entry and
        proved it bad (corrupt/tombstone/skew) — let it heal the key
        instead of pinning the bad entry forever behind first-wins."""
        try:
            published = self.depot.put(key, data, replace=replace)
        except (OSError, ValueError):
            return False
        self.metrics.inc("kft_depot_publishes_total" if published
                         else "kft_depot_publish_races_total")
        return published

    def depot_metrics(self) -> dict:
        """Every kft_depot_* counter (server- and worker-reported) — the
        bench JSON's depot section."""
        with self.metrics._lock:
            return {k: v for k, v in self.metrics._counters.items()
                    if k.startswith("kft_depot_")}

    def job_phases(self, ns: str, job_name: str) -> dict[str, dict]:
        """Heartbeat-transported phase stamps per pod of a job — the
        CURRENT incarnation only (the consumer bench.py decomposes cold
        vs warm-claim from these)."""
        job = self.controller.get(ns, job_name)
        uid = job.uid if job is not None else None
        with self._lock:
            return {pod: dict(ph)
                    for (pns, pjob, puid, pod), ph
                    in self.phase_reports.items()
                    if pns == ns and pjob == job_name and puid == uid}

    def job_recovery(self, ns: str, job_name: str) -> list[dict]:
        """The reconciler's recovery timeline for a job (worker_failed /
        replacement / survivor_restarted / gang_restart events with
        timestamps) — what bench.py decomposes recovery_seconds from,
        joined with the worker phase stamps in ``job_phases``."""
        with self._lock:
            return [dict(e) for e in
                    self.controller.recovery_log.get((ns, job_name), [])]

    def job_trace(self, ns: str, job_name: str) -> list[dict]:
        """The operator-merged job trace: worker phase reports (carried
        over the heartbeat transport) + the reconciler recovery log +
        any explicitly POSTed worker spans, folded into one span list by
        obs/export.build_job_trace. Served at /apis/v1/trace/{ns}/{job}
        (depot-token-fenced); ?format=chrome exports Perfetto JSON.
        Current incarnation only, like job_phases."""
        job = self.controller.get(ns, job_name)
        if job is None:
            return []
        uid = job.uid
        with self._lock:
            phases = {pod: dict(ph)
                      for (pns, pjob, puid, pod), ph
                      in self.phase_reports.items()
                      if pns == ns and pjob == job_name and puid == uid}
            posted = {pod: [dict(s) for s in spans]
                      for (pns, pjob, puid, pod), spans
                      in self.span_reports.items()
                      if pns == ns and pjob == job_name and puid == uid}
            events = [dict(e) for e in
                      self.controller.recovery_log.get((ns, job_name), [])]
        return obs_export.build_job_trace(
            ns, job_name, uid, phases,
            recovery_events=events, worker_spans=posted)

    def _tick_warm_pool(self) -> None:
        """Replenish/reap the warm pool and export its counters — runs on
        the serving period OUTSIDE the operator lock (pool reconcile does
        blocking apiserver HTTP; the pool self-serializes)."""
        pool = self.warm_pool
        if pool is None:
            return
        pool.reconcile()
        snap = pool.snapshot()
        self.metrics.set("kft_warm_pool_standby", snap["standby"])
        # the *_total metrics are COUNTERS: export deltas via inc() so
        # /metrics renders them with counter TYPE (a gauge-typed _total
        # breaks Prometheus rate()/increase())
        last = getattr(self, "_warm_pool_exported", {})
        for k in ("claims", "fallbacks", "dead_claims", "claim_errors",
                  "created", "reaped", "prefetched_entries",
                  "prefetch_errors", "reclaims", "reclaim_noops"):
            self.metrics.inc(f"kft_warm_pool_{k}_total",
                             by=snap[k] - last.get(k, 0))
        self._warm_pool_exported = snap

    def _warning_path(self, job_name: str, pod_name: str, uid: str) -> str:
        # uid-scoped: a deleted-and-resubmitted job (same names, new uid)
        # must NOT inherit the previous incarnation's warnings
        frag = f"-{uid[:8]}" if uid else ""
        return os.path.join(
            self.heartbeat_dir, f"{job_name}-{pod_name}{frag}.warn")

    def _collect_warnings(self, ns: str, name: str):
        """Worker warning files -> job Warning conditions + a metric. The
        reverse of the heartbeat contract: heartbeats say 'alive', warning
        lines say 'alive but degraded' (e.g. CheckpointMirrorDegraded) —
        exactly the state to surface before the slice dies."""
        job = self.controller.get(ns, name)
        if job is None:
            return
        for pod in self.controller.cluster.list_pods(
                ns, {"job-name": name, "job-uid": job.uid}):
            if pod is None:
                continue
            path = self._warning_path(name, pod.name, job.uid)
            pos = self._warn_offsets.get(path, 0)
            try:
                with open(path) as f:
                    f.seek(pos)
                    lines = f.readlines()
                    self._warn_offsets[path] = f.tell()
            except OSError:
                continue
            seen = {(c.reason, c.message) for c in job.status.warnings()}
            for line in lines:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                reason = rec.get("reason", "WorkerWarning")
                msg = rec.get("message", "")
                if (reason, msg) in seen:
                    continue
                seen.add((reason, msg))
                with self._lock:
                    job.status.conditions.append(Condition(
                        type=ConditionType.WARNING,
                        reason=reason, message=msg))
                self.metrics.inc(
                    "kft_worker_warnings_total", labels={"reason": reason})

    def _record_first_step(self, ns: str, name: str):
        key = (ns, name)
        if key in self._first_step_seen or key not in self._submit_times:
            return
        job = self.controller.get(ns, name)
        if job is None:
            return
        for pod in self.controller.cluster.list_pods(
                ns, {"job-name": name, "job-uid": job.uid}):
            if pod is None:
                continue
            path = self.tracker.path_for(name, pod.name)
            try:
                with open(path) as f:
                    step = int(f.read().strip() or 0)
                mtime = os.path.getmtime(path)
            except (OSError, ValueError):
                continue
            if step >= 1:
                self._first_step_seen.add(key)
                self.metrics.set(
                    "kft_submit_to_first_step_seconds",
                    mtime - self._submit_times[key],
                    {"namespace": ns, "job": name},
                )
                return

    def _serving_loop(self):
        while not self._stop.wait(self.serving_period):
            for tick in self.serving_tickers:
                try:
                    tick()
                    self.metrics.inc("kft_serving_ticks_total")
                except Exception:
                    self.metrics.inc("kft_serving_tick_errors_total")

    # ---------------- lifecycle ----------------

    def start(self, port: int = 0, host: str = "127.0.0.1",
              tls_cert: Optional[str] = None,
              tls_key: Optional[str] = None) -> int:
        """Start loops + HTTP server; returns the bound port. In-cluster
        deployments pass host="0.0.0.0" so kubelet probes and Services can
        reach the API; the default stays loopback for local dev. With
        ``tls_cert``/``tls_key`` the API serves HTTPS (the cert-manager
        serving-cert role; see platform.certs.ensure_self_signed)."""
        cluster = self.controller.cluster
        if hasattr(cluster, "start_informer"):
            # kube backend: watch-fed cache serves every read between pod
            # events, and events (not a poll timer) drive reconcile.
            # Subscribe (never overwrite on_pod_event — a second Operator
            # sharing this cluster must not detach the first) and record
            # whether WE started the informer: only the owner stops it.
            self._pod_event_wake = threading.Event()
            self._pod_event_cb = (
                lambda etype, pod: self._pod_event_wake.set())
            if hasattr(cluster, "add_pod_event_listener"):
                cluster.add_pod_event_listener(self._pod_event_cb)
            else:
                cluster.on_pod_event = self._pod_event_cb
            self._informer_owner = bool(cluster.start_informer(
                resync_period_s=self.informer_resync_s))
        self._threads = [
            threading.Thread(target=self._reconcile_loop, daemon=True,
                             name="kft-reconcile"),
        ]
        if self.tracker is not None:
            self._threads.append(threading.Thread(
                target=self._heartbeat_loop, daemon=True, name="kft-heartbeat"))
        if self.serving_tickers:
            self._threads.append(threading.Thread(
                target=self._serving_loop, daemon=True, name="kft-serving"))
        for t in self._threads:
            t.start()
        self._httpd = _make_http_server(self, port, host)
        if tls_cert and tls_key:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            # lazy handshake: accept() must never block the accept loop on
            # a client that connects and sends nothing (TCP healthchecks,
            # scanners) — the handshake runs in the per-connection handler
            # thread on first read instead
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self._httpd.server_address[1]
        if self.advertise_url is None:
            reach = "127.0.0.1" if host in ("0.0.0.0", "::") else host
            scheme = "https" if tls_cert and tls_key else "http"
            self.advertise_url = f"{scheme}://{reach}:{self.port}"
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="kft-http").start()
        return self.port

    def stop(self):
        self._stop.set()
        if self._pod_event_wake is not None:
            self._pod_event_wake.set()       # unblock the reconcile wait
            cluster = self.controller.cluster
            cb = getattr(self, "_pod_event_cb", None)
            if cb is not None and hasattr(cluster,
                                          "remove_pod_event_listener"):
                cluster.remove_pod_event_listener(cb)
            # only the operator whose start_informer() call actually
            # started the thread stops it — a second Operator sharing this
            # KubeCluster must not kill the first one's informer
            # (ADVICE r5 #1)
            if getattr(self, "_informer_owner", False):
                stop_informer = getattr(cluster, "stop_informer", None)
                if stop_informer is not None:
                    stop_informer()
        if self._httpd is not None:
            self._httpd.shutdown()
        for t in self._threads:
            t.join(timeout=5)


def _experiment_to_dict(exp) -> dict:
    best = exp.best_trial
    return {
        "name": exp.name,
        "namespace": exp.namespace,
        "succeeded": exp.succeeded,
        "failed": exp.failed,
        "completion_reason": exp.completion_reason,
        "trials": {s.value: n for s, n in exp.counts().items() if n},
        "trials_total": len(exp.trials),
        "best_trial": (
            {"name": best.name, "parameters": best.parameters,
             "objective_value": best.objective_value}
            if best else None),
    }


def _isvc_to_dict(isvc) -> dict:
    return {
        "name": isvc.name,
        "namespace": isvc.namespace,
        "ready": isvc.status.ready,
        "url": isvc.status.url,
        "latest_revision": isvc.status.latest_revision,
        "traffic": {str(k): v for k, v in isvc.status.traffic.items()},
    }


def _job_to_dict(job) -> dict:
    cond = job.status.condition()
    return {
        "namespace": job.namespace,
        "name": job.name,
        "kind": job.kind,
        "uid": job.uid,
        "condition": cond.value if cond else None,
        "restart_count": job.status.restart_count,
        "worker_replacements": job.status.worker_replacements,
        "rendezvous_epoch": job.status.rendezvous_epoch,
        "conditions": [
            {"type": c.type.value, "reason": c.reason, "message": c.message}
            for c in job.status.conditions
        ],
        "replica_statuses": {
            rt: {"active": rs.active, "succeeded": rs.succeeded,
                 "failed": rs.failed}
            for rt, rs in job.status.replica_statuses.items()
        },
    }


def _run_to_dict(run) -> dict:
    out = {
        "run_id": run.run_id,
        "state": run.state.value,
        "tasks": {n: t.state.value for n, t in run.tasks.items()},
    }
    if getattr(run, "error", ""):
        out["error"] = run.error
    return out


def _make_http_server(op: Operator, port: int,
                      host: str = "127.0.0.1"
                      ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        # 1.1: keep-alive + honest chunked framing for proxied SSE streams
        # (a 1.0 status line with Transfer-Encoding: chunked is malformed
        # for spec-compliant clients)
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet
            pass

        def _send(self, code: int, body: str,
                  ctype: str = "application/json",
                  location: Optional[str] = None):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            if location is not None:
                self.send_header("Location", location)
            self.end_headers()
            self.wfile.write(data)

        def _webui(self, method: str, body: str = ""):
            """Delegate a /ui request: listings scoped to the caller's
            profile namespaces, CRUD re-authorized per target namespace."""
            visible = lambda ns: True          # noqa: E731
            authz = lambda ns, verb: (True, "")  # noqa: E731
            if op.auth is not None:
                user = op.auth.authenticate(
                    self.headers.get("Authorization"))
                profiles = getattr(op.auth, "profiles", None)
                if user not in op.auth.admins and profiles is not None:
                    allowed = set(profiles.namespaces_for(user))
                    visible = lambda ns: ns in allowed  # noqa: E731

                def authz(ns, verb):
                    method = "DELETE" if verb == "delete" else "POST"
                    res = op.auth.check(
                        self.headers.get("Authorization"), method, ns)
                    return res.allowed, res.reason or ""

            resp = op.webui.handle(
                method, self.path.split("?")[0], body,
                visible=visible, authz=authz)
            if resp is None:
                return self._send(404, '{"error": "unknown path"}')
            self._send(resp.code, resp.body, resp.ctype,
                       location=resp.location)

        def _same_site(self) -> bool:
            """CSRF guard for the /ui HTML forms. Browsers never attach the
            bearer Authorization header to a form POST, so the forms only
            work with auth off — exactly the mode where a cross-origin page
            could fire drive-by POSTs at a localhost daemon. Browsers stamp
            cross-origin form posts with ``Sec-Fetch-Site: cross-site``
            and an ``Origin`` header; header-less clients (curl, the test
            suite, the SDK) are same-machine tools and pass. A request
            carrying a bearer token that authenticates is exempt: browsers
            attach Origin/Sec-Fetch-Site to legitimate cross-origin
            authenticated fetch() too, and the token itself already
            defeats CSRF (an attacker page cannot read it)."""
            authz = self.headers.get("Authorization")
            if authz and op.auth is not None \
                    and op.auth.authenticate(authz) is not None:
                return True
            sfs = self.headers.get("Sec-Fetch-Site")
            if sfs is not None and sfs not in (
                    "same-origin", "same-site", "none"):
                return False
            origin = self.headers.get("Origin")
            if origin and origin != "null":
                host = (origin.split("://", 1)[-1]).rstrip("/")
                if host != self.headers.get("Host", ""):
                    return False
            elif origin == "null":
                return False
            return True

        def _depot_path(self) -> Optional[str]:
            # /apis/v1/depot -> ""   /apis/v1/depot/{key} -> key
            parts = self.path.strip("/").split("/")
            if parts[:3] == ["apis", "v1", "depot"] and len(parts) <= 4:
                return parts[3] if len(parts) == 4 else ""
            return None

        def _trace_path(self):
            # /apis/v1/trace/{ns}/{job}[?format=chrome]
            from urllib.parse import parse_qs

            route, _, query = self.path.partition("?")
            parts = route.strip("/").split("/")
            if parts[:3] == ["apis", "v1", "trace"] and len(parts) == 5:
                fmt = (parse_qs(query).get("format") or ["spans"])[0]
                return parts[3], parts[4], fmt
            return None

        def _trace(self, ns: str, job: str, fmt: str):
            """Job-trace route — auth-fenced like the depot endpoint:
            the operator-injected depot token admits workers/tools (they
            hold no bearer tokens), and a bearer token with read rights
            in the namespace admits humans when auth is configured.
            Execution timelines leak workload structure, so with a depot
            configured and no valid credential the route refuses; only a
            depot-less, auth-less local-dev daemon serves it openly
            (matching every other control-plane GET in that mode)."""
            if not op.depot_authorized(
                    self.headers.get(DEPOT_TOKEN_HEADER)):
                if op.auth is not None:
                    res = op.auth.check(
                        self.headers.get("Authorization"), "GET", ns)
                    if not res.allowed:
                        return self._send(
                            res.status, json.dumps({"error": res.reason}))
                elif op.depot is not None:
                    return self._send(
                        403, '{"error": "depot token required"}')
            if op.controller.get(ns, job) is None:
                return self._send(404, '{"error": "unknown job"}')
            spans = op.job_trace(ns, job)
            if fmt == "chrome":
                from kubeflow_tpu.obs.export import chrome_trace

                return self._send(200, json.dumps(chrome_trace(spans)))
            return self._send(200, json.dumps({"spans": spans}))

        def _send_bytes(self, code: int, data: bytes,
                        ctype: str = "application/octet-stream"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _depot(self, method: str, key: str, raw: bytes = b""):
            """Executable-depot routes (see Operator.depot_authorized for
            the trust model). GET "" lists keys (pre-fetch sync), GET key
            streams the entry, POST key publishes first-wins."""
            if not op.depot_authorized(
                    self.headers.get(DEPOT_TOKEN_HEADER)):
                return self._send(
                    403, '{"error": "depot token required"}')
            if method == "GET" and not key:
                try:
                    keys = op.depot.keys()
                except Exception:
                    keys = []
                return self._send(200, json.dumps({"keys": keys}))
            if method == "GET":
                data = op.depot_fetch(key)
                if data is None:
                    return self._send(404, '{"error": "no entry"}')
                return self._send_bytes(200, data)
            if not key:
                return self._send(400, '{"error": "publish needs a key"}')
            published = op.depot_publish(
                key, raw,
                replace=self.headers.get(DEPOT_REPLACE_HEADER) == "1")
            return self._send(200, json.dumps({"published": published}))

        def _heartbeat_path(self):
            # /apis/v1/namespaces/{ns}/jobs/{job}/pods/{pod}/heartbeat[?uid=]
            from urllib.parse import parse_qs

            route, _, query = self.path.partition("?")
            parts = route.strip("/").split("/")
            if (len(parts) == 9 and parts[:3] == ["apis", "v1", "namespaces"]
                    and parts[4] == "jobs" and parts[6] == "pods"
                    and parts[8] == "heartbeat"):
                uid = (parse_qs(query).get("uid") or [""])[0]
                return parts[3], parts[5], parts[7], uid
            return None

        def _resource_path(self, kind: str):
            # /apis/v1/namespaces/{ns}/{kind}[/{name}]
            parts = self.path.strip("/").split("/")
            if (len(parts) >= 4 and parts[0] == "apis" and parts[1] == "v1"
                    and parts[2] == "namespaces" and parts[4:5] == [kind]):
                return parts[3], (parts[5] if len(parts) > 5 else None)
            return None, None

        def _job_path(self):
            return self._resource_path("jobs")

        def _pipeline_path(self):
            # /apis/v1/pipelines[/...] — platform-scoped, not namespaced
            parts = self.path.strip("/").split("/")
            if parts[:3] == ["apis", "v1", "pipelines"]:
                return parts[3:]
            return None

        def _maybe_proxy(self, method: str, body=None) -> bool:
            """Route /serving/{ns}/{name}/<rest> through the ingress
            gateway. Data-plane access needs only read rights in the
            namespace (inference is a 'get', whatever the HTTP verb)."""
            route, _, query = self.path.partition("?")
            parts = route.strip("/").split("/")
            if op.ingress is None or len(parts) < 4 \
                    or parts[0] != "serving":
                return False
            ns, name = parts[1], parts[2]
            rest = "/".join(parts[3:]) + (("?" + query) if query else "")
            if op.auth is not None:
                res = op.auth.check(
                    self.headers.get("Authorization"), "GET", ns)
                if not res.allowed:
                    self._send(res.status, json.dumps({"error": res.reason}))
                    return True
            self.proxy_headers_sent = False
            try:
                op.ingress.proxy(self, method, ns, name, rest, body)
            except Exception as e:
                if not getattr(self, "proxy_headers_sent", False):
                    try:
                        self._send(502, json.dumps({"error": str(e)}))
                    except Exception:
                        pass
                else:
                    # headers (and possibly chunks) already went out: a 502
                    # injected mid-stream would corrupt the framing — drop
                    # the connection so the client sees a truncated stream
                    self.close_connection = True
            return True

        def _path_namespace(self):
            parts = self.path.strip("/").split("/")
            if (len(parts) >= 4 and parts[0] == "apis" and parts[1] == "v1"
                    and parts[2] == "namespaces"):
                return parts[3]
            return None

        def _authorized(self) -> bool:
            """Enforce authn/authz on namespaced routes; sends the error
            response itself when denied."""
            if op.auth is None or self.path in ("/healthz", "/metrics"):
                return True
            res = op.auth.check(self.headers.get("Authorization"),
                                self.command, self._path_namespace())
            if not res.allowed:
                self._send(res.status, json.dumps({"error": res.reason}))
                return False
            return True

        def do_GET(self):
            if self.path == "/healthz":
                return self._send(200, "ok", "text/plain")
            if self.path == "/metrics":
                return self._send(200, op.metrics.render(), "text/plain")
            dp = self._depot_path()
            if dp is not None:
                # worker-facing like the heartbeat sink (workers hold no
                # bearer tokens) — fenced by the depot token instead
                return self._depot("GET", dp)
            tp = self._trace_path()
            if tp is not None:
                return self._trace(*tp)
            if not self._authorized():
                return
            if self._maybe_proxy("GET"):
                return
            if op.webui is not None and (
                    self.path == "/ui" or self.path.startswith("/ui/")):
                return self._webui("GET")
            if self.path in ("/dashboard", "/apis/v1/dashboard") and \
                    op.dashboard is not None:
                user = None
                if op.auth is not None:
                    user = op.auth.authenticate(
                        self.headers.get("Authorization"))
                    if user in op.auth.admins:
                        user = None          # admins see every namespace
                snap = op.dashboard.snapshot(user)
                if self.path == "/apis/v1/dashboard":
                    return self._send(200, json.dumps(snap))
                return self._send(
                    200,
                    op.dashboard.render_html(
                        snap, webui_mounted=op.webui is not None),
                    "text/html")
            ns, name = self._job_path()
            if ns and name:
                job = op.controller.get(ns, name)
                if job is None:
                    return self._send(404, '{"error": "not found"}')
                return self._send(200, json.dumps(_job_to_dict(job)))
            if ns:
                jobs = [_job_to_dict(j) for (jns, _), j in
                        op.controller.jobs.items() if jns == ns]
                return self._send(200, json.dumps({"items": jobs}))
            ns, name = self._resource_path("experiments")
            if ns and op.experiments is not None:
                if name:
                    exp = op.experiments.get(ns, name)
                    if exp is None:
                        return self._send(404, '{"error": "not found"}')
                    return self._send(200,
                                      json.dumps(_experiment_to_dict(exp)))
                return self._send(200, json.dumps({"items": [
                    _experiment_to_dict(e) for e in op.experiments.list()
                    if e.namespace == ns]}))
            ns, name = self._resource_path("inferenceservices")
            if ns and op.serving is not None:
                ctl = op.serving.controller
                if name:
                    isvc = ctl.get(ns, name)
                    if isvc is None:
                        return self._send(404, '{"error": "not found"}')
                    return self._send(200, json.dumps(_isvc_to_dict(isvc)))
                return self._send(200, json.dumps({"items": [
                    _isvc_to_dict(s) for (sns, _), s in ctl.services.items()
                    if sns == ns]}))
            pp = self._pipeline_path()
            if pp is not None and op.pipelines is not None:
                if not pp:
                    return self._send(200, json.dumps(
                        {"items": op.pipelines.list_pipelines()}))
                if pp[0] == "runs":
                    if len(pp) == 2:
                        run = op.pipelines.get_run(pp[1])
                        if run is None:
                            return self._send(404, '{"error": "not found"}')
                        return self._send(200, json.dumps(_run_to_dict(run)))
                    return self._send(200, json.dumps({"items": [
                        _run_to_dict(r) for r in op.pipelines.list_runs()]}))
                if pp[0] == "recurring":
                    return self._send(200, json.dumps({"items": [
                        {"name": rr.name, "pipeline": rr.pipeline,
                         "interval_seconds": rr.interval_seconds,
                         "enabled": rr.enabled, "run_ids": rr.run_ids}
                        for rr in op.pipelines.list_recurring()]}))
            self._send(404, '{"error": "unknown path"}')

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            if not self._same_site():
                # CSRF guard for EVERY mutating route, not just /ui: with
                # auth off, a cross-origin page could otherwise drive-by
                # POST a JobSpec at the localhost daemon (fetch no-cors /
                # text-plain form posts need no preflight)
                return self._send(
                    403, '{"error": "cross-site request rejected"}')
            hb = self._heartbeat_path()
            if hb is not None:
                # worker liveness sink — UNAUTHENTICATED by design: worker
                # pods hold no bearer tokens, and forging a beat only
                # delays fault detection (same trust level as the shared
                # -fs file transport it replaces); warnings are advisory
                try:
                    body_doc = json.loads(raw.decode() or "{}")
                except (json.JSONDecodeError, UnicodeDecodeError):
                    return self._send(400, '{"error": "bad json"}')
                ns_, job_, pod_, uid_ = hb
                ok = op.heartbeat_post(ns_, job_, pod_, body_doc, uid=uid_)
                return self._send(200 if ok else 404,
                                  '{"ok": true}' if ok
                                  else '{"error": "unknown job or uid"}')
            dp = self._depot_path()
            if dp is not None:
                # BEFORE the UTF-8 decode: depot entries are binary
                return self._depot("POST", dp, raw)
            if not self._authorized():
                return
            # proxy BEFORE decoding: inference payloads may be binary
            # (v2 tensor data); only the control-plane routes are text
            if self._maybe_proxy("POST", raw):
                return
            try:
                body = raw.decode()
            except UnicodeDecodeError:
                return self._send(
                    400, '{"error": "control-plane body must be UTF-8"}')
            if op.webui is not None and self.path.startswith("/ui/"):
                return self._webui("POST", body)
            ns, _ = self._job_path()
            if ns:
                try:
                    job = from_yaml(body)   # YAML superset: JSON works too
                    # URL namespace wins (k8s convention); an explicit body
                    # namespace that disagrees is a client error
                    if job.namespace not in ("", "default", ns):
                        raise ValueError(
                            f"body namespace {job.namespace!r} != URL "
                            f"namespace {ns!r}")
                    job.namespace = ns
                    op.submit(job)
                except Exception as e:
                    from kubeflow_tpu.platform.profiles import QuotaExceeded

                    code = 403 if isinstance(e, QuotaExceeded) else 400
                    return self._send(code, json.dumps({"error": str(e)}))
                return self._send(201, json.dumps(_job_to_dict(job)))
            ns, _ = self._resource_path("experiments")
            if ns and op.experiments is not None:
                try:
                    from kubeflow_tpu.hpo.persistence import (
                        experiment_from_dict,
                    )

                    payload = json.loads(body)
                    spec = dict(payload["experiment"])
                    if spec.get("namespace") not in (None, "", ns):
                        raise ValueError(
                            f"body namespace {spec['namespace']!r} != URL "
                            f"namespace {ns!r}")
                    spec["namespace"] = ns
                    exp = experiment_from_dict(spec)
                    with op._lock:
                        op.experiments.submit(exp, payload["trial_template"])
                except Exception as e:
                    return self._send(400, json.dumps({"error": str(e)}))
                return self._send(
                    201, json.dumps(_experiment_to_dict(exp)))
            ns, _ = self._resource_path("inferenceservices")
            if ns and op.serving is not None:
                try:
                    from kubeflow_tpu.serving.types import (
                        inference_service_from_dict,
                    )

                    payload = json.loads(body)
                    if payload.get("namespace") not in (None, "", ns):
                        raise ValueError(
                            f"body namespace {payload['namespace']!r} != "
                            f"URL namespace {ns!r}")
                    payload["namespace"] = ns
                    isvc = inference_service_from_dict(payload)
                    with op._lock:
                        op.serving.controller.apply(isvc)
                except Exception as e:
                    return self._send(400, json.dumps({"error": str(e)}))
                return self._send(201, json.dumps(_isvc_to_dict(isvc)))
            pp = self._pipeline_path()
            if pp is not None and op.pipelines is not None:
                if not self._pipeline_write_allowed():
                    return
                try:
                    if not pp:
                        # upload a compiled IR document (YAML or JSON)
                        import yaml as _yaml

                        name = op.pipelines.upload_ir(_yaml.safe_load(body))
                        return self._send(201, json.dumps({"name": name}))
                    if len(pp) == 2 and pp[1] == "runs":
                        # launch asynchronously: a pipeline can run for
                        # minutes — the POST returns 202 + run_id and the
                        # client polls the (store-backed) run status
                        payload = json.loads(body or "{}")
                        try:
                            run_id = op.pipelines.create_run_async(
                                pp[0], arguments=payload.get("arguments"),
                                run_id=payload.get("run_id"))
                        except KeyError:
                            return self._send(
                                404, '{"error": "unknown pipeline"}')
                        return self._send(
                            202, json.dumps({"run_id": run_id}))
                    if pp == ["recurring"]:
                        payload = json.loads(body)
                        rr = op.pipelines.create_recurring_run(
                            payload["name"], payload["pipeline"],
                            float(payload["interval_seconds"]),
                            arguments=payload.get("arguments"),
                            max_concurrency=int(
                                payload.get("max_concurrency", 1)))
                        return self._send(201, json.dumps(
                            {"name": rr.name, "enabled": rr.enabled}))
                except Exception as e:
                    return self._send(400, json.dumps({"error": str(e)}))
            self._send(404, '{"error": "unknown path"}')

        def _pipeline_write_allowed(self) -> bool:
            """Pipeline mutations are platform-scoped AND execute imported
            component code in the daemon process, so with auth enabled
            they are admin-only; sends the error itself when denied."""
            if op.auth is None:
                return True
            user = op.auth.authenticate(self.headers.get("Authorization"))
            if user in op.auth.admins:
                return True
            self._send(403, json.dumps(
                {"error": "pipeline writes require an admin token"}))
            return False

        def do_DELETE(self):
            if not self._same_site():
                return self._send(
                    403, '{"error": "cross-site request rejected"}')
            if not self._authorized():
                return
            ns, name = self._job_path()
            if ns and name:
                op.delete(ns, name)
                return self._send(200, "{}")
            ns, name = self._resource_path("experiments")
            if ns and name and op.experiments is not None:
                with op._lock:
                    op.experiments.delete(ns, name)
                return self._send(200, "{}")
            ns, name = self._resource_path("inferenceservices")
            if ns and name and op.serving is not None:
                with op._lock:
                    op.serving.controller.delete(ns, name)
                return self._send(200, "{}")
            pp = self._pipeline_path()
            if (pp is not None and len(pp) == 2 and pp[0] == "recurring"
                    and op.pipelines is not None):
                if not self._pipeline_write_allowed():
                    return
                try:
                    op.pipelines.disable_recurring_run(pp[1])
                except KeyError:
                    return self._send(404, '{"error": "not found"}')
                return self._send(200, "{}")
            self._send(404, '{"error": "unknown path"}')

    return ThreadingHTTPServer((host, port), Handler)
