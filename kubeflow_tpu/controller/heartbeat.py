"""Heartbeat-based failure detection (SURVEY.md §2.8 fault signaling, §5
failure detection): workers beat a liveness file (training.loop.Heartbeat);
the controller tracks staleness and fails stale pods so the gang-restart +
checkpoint-resume path kicks in. Catches hangs that exit codes never
surface (a wedged collective keeps the process alive forever)."""

from __future__ import annotations

import os
import time
from typing import Optional

from kubeflow_tpu.controller.cluster import PodPhase
from kubeflow_tpu.controller.reconciler import JobController


class FileHeartbeatTracker:
    """Reads worker heartbeat files; a pod whose file mtime is older than
    ``timeout_s`` (or missing past the grace window) is stale."""

    def __init__(self, heartbeat_dir: str, timeout_s: float = 120.0,
                 startup_grace_s: float = 300.0):
        self.dir = heartbeat_dir
        self.timeout_s = timeout_s
        self.startup_grace_s = startup_grace_s
        os.makedirs(heartbeat_dir, exist_ok=True)

    def path_for(self, job_name: str, pod_name: str) -> str:
        return os.path.join(self.dir, f"{job_name}-{pod_name}.hb")

    def is_stale(self, job_name: str, pod_name: str,
                 pod_started_at: float,
                 now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        path = self.path_for(job_name, pod_name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            # never beat: stale only after the startup grace window
            return now - pod_started_at > self.startup_grace_s
        if mtime < pod_started_at:
            # the beat predates this pod INCARNATION (a replaced/restarted
            # worker under the same name): a dead incarnation's last beat
            # must not fail the fresh pod — it gets the startup grace,
            # like a pod that never beat
            return now - pod_started_at > self.startup_grace_s
        return now - mtime > self.timeout_s


def check_heartbeats(controller: JobController, namespace: str, name: str,
                     tracker: FileHeartbeatTracker,
                     now: Optional[float] = None) -> list[str]:
    """Fail pods with stale heartbeats; the next reconcile turns any failure
    into a gang restart (ICI worlds can't lose a member). Returns the stale
    pod names."""
    job = controller.get(namespace, name)
    if job is None or job.status.is_finished():
        return []
    stale = []
    for pod in controller.cluster.list_pods(
            namespace, {"job-name": name, "job-uid": job.uid}):
        if pod is None or pod.phase != PodPhase.RUNNING:
            continue
        if tracker.is_stale(name, pod.name, pod.created_at, now=now):
            pod.phase = PodPhase.FAILED
            pod.exit_code = -1          # signal-ish: retryable
            stale.append(pod.name)
    if stale:
        controller.reconcile(namespace, name)
    return stale
