"""Gang scheduling: all-or-nothing admission of a job's pod group onto
whole TPU slices.

The reference delegates this to volcano/scheduler-plugins PodGroups
(SURVEY.md §2.1 'Gang-scheduling glue', §7 hard part #1: partial-slice
deadlock is the failure mode). TPU slices make it stricter than generic
gang scheduling: the atom of placement is a *slice* (a topology like
"4x4" = 16 chips = 4 hosts), a slice belongs to at most one job, and a
multi-host job is either one slice of sufficient shape or k identical
whole slices (multislice over DCN). Placing part of a job — or two jobs
on one slice — is useless, so admission reserves whole slices atomically
or not at all.

Starvation control: pure backfill (small jobs admitted past a blocked
large one) would starve the large job forever under churn. A pending
group older than ``aging_s`` becomes a head-of-line blocker for its
pool: nothing younger is admitted from that pool until it fits.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional


def topology_hosts(topology: str, chips_per_host: int = 4) -> int:
    """Hosts in a slice topology string, e.g. "4x4" -> 16 chips -> 4 hosts."""
    chips = math.prod(int(x) for x in topology.split("x"))
    return max(1, chips // chips_per_host)


@dataclasses.dataclass
class TpuSlice:
    """One physical TPU slice: the unit of allocation."""

    id: str
    topology: str = "2x2"             # chip grid; 4 chips = 1 host default
    chips_per_host: int = 4
    allocated_to: Optional[tuple[str, str]] = None   # (namespace, name)

    @property
    def hosts(self) -> int:
        return topology_hosts(self.topology, self.chips_per_host)

    @property
    def free(self) -> bool:
        return self.allocated_to is None


@dataclasses.dataclass
class SlicePool:
    """The slices of one accelerator type (e.g. four v5p "2x2x4" slices).

    Legacy host-count construction (``SlicePool(total_hosts=8)``) models the
    capacity as single-host slices, preserving the old integer semantics.
    """

    accelerator: str = "any"
    slices: Optional[list[TpuSlice]] = None
    total_hosts: dataclasses.InitVar[Optional[int]] = None
    free_hosts: dataclasses.InitVar[Optional[int]] = None   # legacy, ignored

    def __post_init__(self, total_hosts, free_hosts):
        if self.slices is None:
            n = 64 if total_hosts is None else total_hosts
            self.slices = [
                TpuSlice(id=f"{self.accelerator}-{i}") for i in range(n)
            ]

    @property
    def capacity_hosts(self) -> int:
        return sum(s.hosts for s in self.slices)

    @property
    def available_hosts(self) -> int:
        return sum(s.hosts for s in self.slices if s.free)

    def find_allocation(self, n_hosts: int) -> Optional[list[TpuSlice]]:
        """Whole slices for an n_hosts job, or None. Preference order:
        (1) one exact-fit slice; (2) k identical slices with
        k*hosts == n_hosts (multislice, fewest slices); (3) one larger
        slice (whole-slice owned: the stranded hosts stay with the job,
        never shared)."""
        free = [s for s in self.slices if s.free]
        single = sorted((s for s in free if s.hosts >= n_hosts),
                        key=lambda s: s.hosts)
        if single and single[0].hosts == n_hosts:
            return [single[0]]
        by_size: dict[int, list[TpuSlice]] = {}
        for s in free:
            by_size.setdefault(s.hosts, []).append(s)
        for h in sorted(by_size, reverse=True):      # fewest slices first
            if n_hosts % h == 0 and len(by_size[h]) >= n_hosts // h:
                return by_size[h][: n_hosts // h]
        if single:
            return [single[0]]
        return None

    def allocate(self, n_hosts: int, key: tuple[str, str]
                 ) -> Optional[list[TpuSlice]]:
        chosen = self.find_allocation(n_hosts)
        if chosen is None:
            return None
        for s in chosen:
            s.allocated_to = key
        return chosen

    def release(self, key: tuple[str, str]) -> None:
        for s in self.slices:
            if s.allocated_to == key:
                s.allocated_to = None


@dataclasses.dataclass
class PodGroup:
    name: str
    namespace: str
    min_member: int
    queue: str = "default"
    priority: int = 0
    admitted: bool = False
    created_at: float = dataclasses.field(default_factory=time.time)


class GangScheduler:
    """Priority/FIFO queue with atomic whole-slice admission.

    Admission is all-or-nothing per PodGroup: either the slices covering
    `min_member` hosts are reserved atomically or the group stays queued
    holding NOTHING — no partial placement, no deadlock from two
    half-placed jobs holding each other's hosts. Backfill past a blocked
    group is allowed only until that group has waited ``aging_s``.
    """

    def __init__(self, pools: Optional[dict[str, SlicePool]] = None,
                 aging_s: float = 300.0):
        self.pools = pools or {"any": SlicePool()}
        for name, pool in self.pools.items():
            if pool.accelerator == "any" and name != "any":
                pool.accelerator = name
        self.aging_s = aging_s
        self.groups: dict[tuple[str, str], PodGroup] = {}
        self.reservations: dict[tuple[str, str], tuple[str, list[str]]] = {}

    def add_group(self, group: PodGroup, accelerator: str = "any") -> None:
        key = (group.namespace, group.name)
        if key not in self.groups:
            self.groups[key] = group
            self.reservations.setdefault(key, (accelerator, []))

    def remove_group(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        self.groups.pop(key, None)
        acc, slice_ids = self.reservations.pop(key, ("any", []))
        if slice_ids:
            self._pool_for(acc).release(key)

    def _pool_for(self, acc: str) -> Optional[SlicePool]:
        return self.pools.get(acc) or self.pools.get("any")

    def try_admit(self, now: Optional[float] = None) -> list[PodGroup]:
        """Admit queued groups in priority order (then FIFO). Returns newly
        admitted groups. A group pending longer than ``aging_s`` blocks
        backfill in its pool so churn cannot starve it."""
        now = time.time() if now is None else now
        admitted = []
        blocked_pools: set[int] = set()
        pending = sorted(
            (g for g in self.groups.values() if not g.admitted),
            key=lambda g: (-g.priority, g.created_at),
        )
        for group in pending:
            key = (group.namespace, group.name)
            acc, _ = self.reservations[key]
            pool = self._pool_for(acc)
            if pool is None or id(pool) in blocked_pools:
                continue
            slices = pool.allocate(group.min_member, key)
            if slices is not None:
                self.reservations[key] = (
                    acc if acc in self.pools else "any",
                    [s.id for s in slices])
                group.admitted = True
                admitted.append(group)
            elif now - group.created_at >= self.aging_s:
                # aged head-of-line: stop backfilling this pool
                blocked_pools.add(id(pool))
        return admitted

    def is_admitted(self, namespace: str, name: str) -> bool:
        g = self.groups.get((namespace, name))
        return bool(g and g.admitted)

    def slice_ids(self, namespace: str, name: str) -> list[str]:
        """Slice ids reserved for an admitted group (placement hints for
        pod node selectors)."""
        return list(self.reservations.get((namespace, name), ("any", []))[1])

    def slice_allocation(self, namespace: str, name: str
                         ) -> list[tuple[str, int]]:
        """-> [(slice_id, hosts)] reserved for an admitted group, in
        reservation order — the shape pod placement fills host by host."""
        acc, ids = self.reservations.get((namespace, name), ("any", []))
        pool = self._pool_for(acc)
        if pool is None:
            return []
        by_id = {s.id: s.hosts for s in pool.slices}
        return [(sid, by_id.get(sid, 1)) for sid in ids]
