"""Gang scheduling: all-or-nothing admission of a job's pod group.

The reference delegates this to volcano/scheduler-plugins PodGroups
(SURVEY.md §2.1 'Gang-scheduling glue', §7 hard part #1: partial-slice
deadlock is the failure mode). TPU slices make it stricter: a JAXJob's
workers are the hosts of ONE slice — placing some of them is useless, so
admission is atomic over slice capacity.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class PodGroup:
    name: str
    namespace: str
    min_member: int
    queue: str = "default"
    priority: int = 0
    admitted: bool = False
    created_at: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass
class SlicePool:
    """Capacity of one TPU slice type (e.g. 16 hosts of v5p in 4 slices)."""

    accelerator: str = "any"
    total_hosts: int = 64
    free_hosts: int = 64


class GangScheduler:
    """Priority/FIFO queue with atomic admission against host capacity.

    Admission is all-or-nothing per PodGroup: either `min_member` hosts are
    reserved atomically or the group stays queued — no partial placement, no
    deadlock from two half-placed jobs holding each other's hosts.
    """

    def __init__(self, pools: Optional[dict[str, SlicePool]] = None):
        self.pools = pools or {"any": SlicePool()}
        self.groups: dict[tuple[str, str], PodGroup] = {}
        self.reservations: dict[tuple[str, str], tuple[str, int]] = {}

    def add_group(self, group: PodGroup, accelerator: str = "any") -> None:
        key = (group.namespace, group.name)
        if key not in self.groups:
            self.groups[key] = group
            self.reservations.setdefault(key, (accelerator, 0))

    def remove_group(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        group = self.groups.pop(key, None)
        acc, held = self.reservations.pop(key, ("any", 0))
        if group and held:
            self.pools[acc].free_hosts += held

    def try_admit(self) -> list[PodGroup]:
        """Admit queued groups in priority order (then FIFO). Returns newly
        admitted groups."""
        admitted = []
        pending = sorted(
            (g for g in self.groups.values() if not g.admitted),
            key=lambda g: (-g.priority, g.created_at),
        )
        for group in pending:
            key = (group.namespace, group.name)
            acc, _ = self.reservations[key]
            pool = self.pools.get(acc) or self.pools.get("any")
            if pool is None:
                continue
            if pool.free_hosts >= group.min_member:
                pool.free_hosts -= group.min_member
                self.reservations[key] = (acc if acc in self.pools else "any",
                                          group.min_member)
                group.admitted = True
                admitted.append(group)
            # strict FIFO head-of-line within a pool would starve large jobs
            # forever under churn; we keep scanning so smaller jobs backfill,
            # but priority ordering ensures head jobs win ties.
        return admitted

    def is_admitted(self, namespace: str, name: str) -> bool:
        g = self.groups.get((namespace, name))
        return bool(g and g.admitted)
