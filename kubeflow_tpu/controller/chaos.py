"""Fault injection (SURVEY.md §5 'failure detection / fault injection').

The reference validates recovery with chaos tooling that deletes pods at
random; this is the first-party equivalent over this repo's cluster
backends — a harness the elasticity tests (and operators debugging
recovery) drive:

- FakeCluster: victims flip to FAILED with a retryable exit code.
- LocalProcessCluster: victims get SIGKILL (exit < 0 — what the
  EXIT_CODE restart policy classifies as retryable), exactly the
  slice-preemption signature at scale.
- KubeCluster: victims die through the same surfaces the kube e2e rig
  uses — when a FakeKubelet is attached, the pod's REAL process is
  SIGKILLed out of the kubelet's process table (the kubelet then reports
  the terminal phase through the apiserver, exactly like a preempted
  node); without one, the fake apiserver's status subresource plays the
  kubelet and flips the phase directly. Killing a claimed warm-pool
  standby kills its resident zygote, which takes the forked worker with
  it (PDEATHSIG) — the preemption signature for warm pods.

``max_kills`` is a hard budget enforced under a lock: concurrent
scheduled-kill ticks and direct ``kill_pod`` calls reserve a slot before
touching a victim, so the blast radius can never overshoot by a race.
"""

from __future__ import annotations

import random
import signal
import threading
import time
from typing import Optional

from kubeflow_tpu.controller.cluster import (
    FakeCluster, LocalProcessCluster, PodPhase,
)


class FaultInjector:
    """Kill pods of a cluster, one-shot or on a background schedule.

    ``kubelet``: the image-less node agent backing a KubeCluster rig
    (controller/kubelet.py) — when given, kube kills go through its real
    process table instead of a status PATCH.
    """

    def __init__(self, cluster, seed: int = 0, kubelet=None):
        self.cluster = cluster
        self.kubelet = kubelet
        self.rng = random.Random(seed)
        self.kills: list[tuple[str, str]] = []     # (namespace, pod name)
        self.max_kills: Optional[int] = None
        self._lock = threading.Lock()
        self._reserved = 0          # kill slots handed out (budget fence)
        # victims currently being killed: two concurrent kill_pod calls
        # on the SAME pod must not both commit (one death, one budget
        # slot). Entries live only for the kill's duration — a respawned
        # pod under the same name is a fresh, killable victim.
        self._in_flight: set[tuple[str, str]] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --------------------------------------------------------- budget --

    def _reserve_kill(self) -> bool:
        with self._lock:
            if self.max_kills is not None \
                    and self._reserved >= self.max_kills:
                return False
            self._reserved += 1
            return True

    def _commit_kill(self, namespace: str, name: str) -> None:
        with self._lock:
            self.kills.append((namespace, name))

    def _release_kill(self) -> None:
        with self._lock:
            self._reserved -= 1

    # ------------------------------------------------------- one-shot --

    def kill_pod(self, namespace: str, name: str) -> bool:
        """Fail one pod the way a preempted TPU host fails. Returns whether
        a live victim was actually hit; respects the ``max_kills`` budget
        even under concurrent callers, and never double-commits one death
        (the loser of a same-victim race reports False)."""
        victim = (namespace, name)
        with self._lock:
            if victim in self._in_flight:
                return False
            self._in_flight.add(victim)
        try:
            if not self._reserve_kill():
                return False
            if self._kill_pod(namespace, name):
                self._commit_kill(namespace, name)
                return True
            self._release_kill()
            return False
        finally:
            with self._lock:
                self._in_flight.discard(victim)

    def _kill_pod(self, namespace: str, name: str) -> bool:
        if isinstance(self.cluster, LocalProcessCluster):
            proc = self.cluster.procs.get((namespace, name))
            if proc is None or proc.poll() is not None:
                return False
            proc.send_signal(signal.SIGKILL)
            return True
        if isinstance(self.cluster, FakeCluster):
            pod = self.cluster.get_pod(namespace, name)
            if pod is None or pod.phase not in (PodPhase.PENDING,
                                                PodPhase.RUNNING):
                return False
            self.cluster.set_phase(namespace, name, PodPhase.FAILED,
                                   exit_code=-9)
            return True
        from kubeflow_tpu.controller.kube import KubeApiError, KubeCluster

        if isinstance(self.cluster, KubeCluster):
            pod = self.cluster.get_pod(namespace, name)
            if pod is None or pod.phase not in (PodPhase.PENDING,
                                                PodPhase.RUNNING):
                return False
            # the pod may be served by a claimed warm standby under its
            # own name — kill the process that ACTUALLY backs it
            victim = (pod.namespace, pod.name)
            proc = (self.kubelet.procs.get(victim)
                    if self.kubelet is not None else None)
            if proc is not None and proc.poll() is None:
                # real preemption: SIGKILL the node-local process; the
                # kubelet's next sync reports FAILED with a signal exit
                # code through the apiserver — the full detection path
                proc.send_signal(signal.SIGKILL)
                return True
            # no node agent (envtest-style rig): play the kubelet via the
            # status subresource, like FakeCluster.set_phase
            try:
                self.cluster.set_phase(pod.namespace, pod.name,
                                       PodPhase.FAILED, exit_code=-9)
            except (KubeApiError, OSError):
                return False
            return True
        raise TypeError(f"unsupported cluster {type(self.cluster).__name__}")

    def kill_random(self, namespace: str,
                    selector: Optional[dict] = None) -> Optional[str]:
        """Kill one random matching live pod; returns its name or None."""
        pods = [p for p in self.cluster.list_pods(namespace, selector or {})
                if p is not None and p.phase in (PodPhase.PENDING,
                                                 PodPhase.RUNNING)]
        self.rng.shuffle(pods)
        for pod in pods:
            if self.kill_pod(namespace, pod.name):
                return pod.name
        return None

    def kill_stage(self, namespace: str, job: str,
                   stage: int) -> Optional[str]:
        """Kill the live pod serving one MPMD pipeline STAGE of ``job``
        (targeted chaos for the elastic-pipeline bench: aim at a specific
        stage deterministically instead of whoever kill_random draws).
        Selects by the reconciler-stamped ``pipeline-stage`` pod label and
        goes through the same lock-fenced ``max_kills`` budget as every
        other kill. Returns the victim pod name or None."""
        return self.kill_random(namespace, {
            "job-name": job, "pipeline-stage": str(stage)})

    def wait_for_kill(self, n: int = 1, timeout_s: float = 30.0) -> bool:
        """Block until at least ``n`` kills landed (bench/test barrier)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if len(self.kills) >= n:
                    return True
            time.sleep(0.02)
        return False

    # ------------------------------------------------------ schedule --

    def start(self, namespace: str, selector: Optional[dict] = None, *,
              period_s: float = 1.0, kill_probability: float = 1.0,
              max_kills: Optional[int] = None) -> None:
        """Background chaos: every ``period_s``, with ``kill_probability``,
        kill one random matching pod, up to ``max_kills`` victims (the
        budget also binds concurrent direct ``kill_pod`` calls)."""
        with self._lock:
            self.max_kills = max_kills

        def loop():
            while not self._stop.wait(period_s):
                with self._lock:
                    # exit on COMMITTED kills only: transient in-flight
                    # reservations (a concurrent kill_pod mid-check that
                    # may yet release its slot) must not end scheduled
                    # chaos below budget — the reserve fence alone stops
                    # overshoot
                    if max_kills is not None \
                            and len(self.kills) >= max_kills:
                        return
                if self.rng.random() <= kill_probability:
                    self.kill_random(namespace, selector)

        self._stop = threading.Event()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kft-chaos")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
