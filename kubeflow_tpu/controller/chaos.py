"""Fault injection (SURVEY.md §5 'failure detection / fault injection').

The reference validates recovery with chaos tooling that deletes pods at
random; this is the first-party equivalent over this repo's cluster
backends — a harness the elasticity tests (and operators debugging
recovery) drive:

- FakeCluster: victims flip to FAILED with a retryable exit code.
- LocalProcessCluster: victims get SIGKILL (exit < 0 — what the
  EXIT_CODE restart policy classifies as retryable), exactly the
  slice-preemption signature at scale.
"""

from __future__ import annotations

import random
import signal
import threading
from typing import Optional

from kubeflow_tpu.controller.cluster import (
    FakeCluster, LocalProcessCluster, PodPhase,
)


class FaultInjector:
    """Kill pods of a cluster, one-shot or on a background schedule."""

    def __init__(self, cluster, seed: int = 0):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.kills: list[tuple[str, str]] = []     # (namespace, pod name)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- one-shot

    def kill_pod(self, namespace: str, name: str) -> bool:
        """Fail one pod the way a preempted TPU host fails. Returns whether
        a live victim was actually hit."""
        if isinstance(self.cluster, LocalProcessCluster):
            proc = self.cluster.procs.get((namespace, name))
            if proc is None or proc.poll() is not None:
                return False
            proc.send_signal(signal.SIGKILL)
            self.kills.append((namespace, name))
            return True
        if isinstance(self.cluster, FakeCluster):
            pod = self.cluster.get_pod(namespace, name)
            if pod is None or pod.phase not in (PodPhase.PENDING,
                                                PodPhase.RUNNING):
                return False
            self.cluster.set_phase(namespace, name, PodPhase.FAILED,
                                   exit_code=-9)
            self.kills.append((namespace, name))
            return True
        raise TypeError(f"unsupported cluster {type(self.cluster).__name__}")

    def kill_random(self, namespace: str,
                    selector: Optional[dict] = None) -> Optional[str]:
        """Kill one random matching live pod; returns its name or None."""
        pods = [p for p in self.cluster.list_pods(namespace, selector or {})
                if p is not None and p.phase in (PodPhase.PENDING,
                                                 PodPhase.RUNNING)]
        self.rng.shuffle(pods)
        for pod in pods:
            if self.kill_pod(namespace, pod.name):
                return pod.name
        return None

    # ------------------------------------------------------------ schedule

    def start(self, namespace: str, selector: Optional[dict] = None, *,
              period_s: float = 1.0, kill_probability: float = 1.0,
              max_kills: Optional[int] = None) -> None:
        """Background chaos: every ``period_s``, with ``kill_probability``,
        kill one random matching pod, up to ``max_kills`` victims."""

        def loop():
            while not self._stop.wait(period_s):
                if max_kills is not None and len(self.kills) >= max_kills:
                    return
                if self.rng.random() <= kill_probability:
                    self.kill_random(namespace, selector)

        self._stop = threading.Event()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kft-chaos")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
