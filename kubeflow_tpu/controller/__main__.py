"""Operator CLI: ``python -m kubeflow_tpu.controller serve``.

The deployable long-running controller process (SURVEY.md §2.1 operator
entrypoint). Flags follow the reference's binary-flag tier (SURVEY.md §5
config system); everything else comes from the job specs themselves.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    """The operator's flag surface — importable so the install-manifest
    tests can validate rendered Deployment args against the REAL parser."""
    parser = argparse.ArgumentParser(prog="kubeflow_tpu.controller")
    sub = parser.add_subparsers(dest="cmd", required=True)
    serve = sub.add_parser("serve", help="run the operator daemon")
    serve.add_argument("--port", type=int, default=8080,
                       help="HTTP port for API + /metrics (0 = ephemeral)")
    serve.add_argument("--bind-host", default="127.0.0.1",
                       help="API bind address; in-cluster Deployments pass "
                            "0.0.0.0 so probes/Services can reach it")
    serve.add_argument("--cluster", choices=("local", "fake", "kube"),
                       default="local",
                       help="pod backend: local subprocesses, in-memory, "
                            "or a Kubernetes apiserver (--apiserver)")
    serve.add_argument("--apiserver", default=None,
                       help="Kubernetes apiserver URL for --cluster kube; "
                            "defaults to the in-cluster env "
                            "(KUBERNETES_SERVICE_HOST) when unset")
    serve.add_argument("--kube-image", default="kubeflow-tpu/runtime:latest",
                       help="default worker image for --cluster kube pods")
    serve.add_argument("--advertise-url", default=None,
                       help="base URL worker pods reach this daemon at "
                            "(heartbeat POSTs on --cluster kube); "
                            "in-cluster: the operator Service DNS")
    serve.add_argument("--config", default=None,
                       help="platform config JSON (the ConfigMap tier); "
                            "flags below override it")
    serve.add_argument("--heartbeat-dir", default=None)
    serve.add_argument("--heartbeat-timeout", type=float, default=None)
    serve.add_argument("--reconcile-period", type=float, default=None)
    serve.add_argument("--warm-pool-size", type=int, default=None,
                       help="pre-warmed standby zygote pods kept per pool "
                            "class on --cluster kube (0 = disabled); "
                            "admission claims one instead of cold-starting. "
                            "On --cluster local any value > 0 enables the "
                            "daemon-resident zygote (warm forks + the "
                            "per-worker elastic replacement path)")
    serve.add_argument("--log-dir", default=None)
    serve.add_argument("--state-dir", default=None,
                       help="durable platform state (metadata WAL, HPO "
                            "trial metrics)")
    serve.add_argument("--auth-tokens", default=None,
                       help="JSON file with bearer tokens + profile "
                            "bindings; omit for an open (dev) API")
    serve.add_argument("--tls-dir", default=None,
                       help="serve the API over HTTPS; a self-signed pair "
                            "is bootstrapped here if absent (drop real PKI "
                            "cert.pem/key.pem in to replace it)")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from kubeflow_tpu.controller.cluster import FakeCluster, LocalProcessCluster
    from kubeflow_tpu.controller.operator import Operator
    from kubeflow_tpu.controller.reconciler import JobController
    from kubeflow_tpu.hpo.manager import ExperimentManager
    from kubeflow_tpu.hpo.persistence import ExperimentStore
    from kubeflow_tpu.metadata.store import MetadataStore
    from kubeflow_tpu.platform.config import load_config
    from kubeflow_tpu.serving.controller import (
        Autoscaler, RuntimeRegistry, ServingController, ServingTicker,
    )

    # three config tiers: dataclass defaults < --config file < flags
    cfg = load_config(args.config, overrides={
        "heartbeat_dir": args.heartbeat_dir,
        "heartbeat_timeout_s": args.heartbeat_timeout,
        "reconcile_period": args.reconcile_period,
        "log_dir": args.log_dir,
        "state_dir": args.state_dir,
        "warm_pool_size": args.warm_pool_size,
    })

    warm_pool = None
    if args.cluster == "kube":
        import os as _os

        from kubeflow_tpu.controller.kube import JobCRStore, KubeCluster

        if not args.advertise_url:
            # the loopback fallback would have every worker pod POST its
            # heartbeats to ITSELF — beats black-hole and healthy jobs
            # gang-restart after the grace window with no diagnostic
            raise SystemExit(
                "--cluster kube needs --advertise-url (the URL worker "
                "pods reach this daemon at, e.g. the operator Service "
                "DNS http://kft-operator.<ns>:8080)")
        url = args.apiserver
        if url is None:
            host = _os.environ.get("KUBERNETES_SERVICE_HOST")
            if not host:
                raise SystemExit(
                    "--cluster kube needs --apiserver or the in-cluster "
                    "KUBERNETES_SERVICE_HOST env")
            url = (f"https://{host}:"
                   f"{_os.environ.get('KUBERNETES_SERVICE_PORT', '443')}")
        cluster = KubeCluster(url, image=args.kube_image)
        controller = JobController(cluster)
        if cfg.warm_pool_size > 0:
            from kubeflow_tpu.controller.warmpool import WarmPoolController

            # pre-warmed standby pods: admission claims one (fork from a
            # node-resident zygote) instead of cold-scheduling; the
            # operator ticks replenish/reap and exports the counters
            warm_pool = WarmPoolController(
                cluster, size=cfg.warm_pool_size,
                classes=cfg.warm_pool_classes,
                reap_s=cfg.warm_pool_reap_s, image=args.kube_image)
        # jobs live as CRs in the apiserver (the etcd role): a restarted
        # controller reloads them and adopts its existing pods (uid
        # round-trips, so the job-uid pod selector still matches)
        controller.job_store = JobCRStore(cluster)
        for job in controller.job_store.load_all():
            controller.restore(job)
    else:
        # local warm pool: the daemon-resident pre-imported zygote. Also
        # what marks the cluster warm-CAPABLE for the reconciler's
        # per-worker elastic replacement (a dead worker respawns warm in
        # place of a whole-gang restart)
        cluster = (LocalProcessCluster(log_dir=cfg.log_dir,
                                       warm_pool=cfg.warm_pool_size > 0)
                   if args.cluster == "local" else FakeCluster())
        controller = JobController(cluster)
    controller.scheduler.aging_s = cfg.gang_aging_s

    # the whole platform in one daemon: training jobs + HPO experiments
    # (durable via the metadata WAL — a restart resumes unfinished sweeps)
    # + serving reconcile/autoscale
    import os

    os.makedirs(cfg.state_dir, exist_ok=True)
    store = ExperimentStore(MetadataStore(
        wal_path=os.path.join(cfg.state_dir, "metadata.wal")))
    experiments = ExperimentManager(
        controller, metrics_dir=os.path.join(cfg.state_dir, "trial-metrics"),
        store=store)
    resumed = experiments.resume_persisted()
    # default runtimes so a POSTed InferenceService is servable out of the
    # box: the first-party predictor entrypoint for llama/jax formats
    from kubeflow_tpu.serving.types import ModelFormat, ServingRuntime

    registry = RuntimeRegistry()
    registry.register(ServingRuntime(
        name="kft-runtime",
        supported_formats=[ModelFormat("llama"), ModelFormat("jax")],
        command=[sys.executable, "-m", "kubeflow_tpu.serving.runtime"]))
    serving = ServingTicker(
        ServingController(cluster, registry), Autoscaler())

    # notebooks + tensorboards (the CRUD-web-app CR targets) and the
    # pipelines API server role share this daemon; pipeline lineage goes
    # through the SAME durable metadata store as HPO, so run state
    # survives restarts (the persistence-agent role)
    from kubeflow_tpu.pipelines.client import PipelineClient
    from kubeflow_tpu.pipelines.runner import LocalRunner
    from kubeflow_tpu.platform.notebooks import (
        NotebookController, TensorBoardController,
    )

    notebooks = NotebookController(cluster)
    tensorboards = TensorBoardController(cluster)
    pipelines = PipelineClient(LocalRunner(
        workdir=os.path.join(cfg.state_dir, "pipelines"),
        metadata=store.backend))
    resumed_pipelines = pipelines.resume_persisted()

    auth = None
    if args.auth_tokens:
        from kubeflow_tpu.platform.auth import Auth

        auth = Auth.from_file(args.auth_tokens)

    # the dashboard is part of the single binary: live views over the same
    # controllers this daemon reconciles, scoped by the auth profiles
    from kubeflow_tpu.platform.dashboard import Dashboard
    from kubeflow_tpu.platform.webui import WebUI

    dashboard = Dashboard(
        jobs=controller, experiments=experiments.list,
        serving=serving.controller, pipelines=pipelines,
        notebooks=notebooks,
        profiles=auth.profiles if auth is not None else None)

    op = Operator(
        controller,
        heartbeat_dir=cfg.heartbeat_dir,
        heartbeat_timeout_s=cfg.heartbeat_timeout_s,
        startup_grace_s=cfg.startup_grace_s,
        reconcile_period=cfg.reconcile_period,
        heartbeat_period=cfg.heartbeat_period,
        serving_period=cfg.serving_period,
        experiment_manager=experiments,
        serving_ticker=serving,
        auth=auth,
        dashboard=dashboard,
        advertise_url=args.advertise_url,
        warm_pool=warm_pool,
        webui=WebUI(jobs=controller, experiments=experiments,
                    serving=serving.controller, pipelines=pipelines,
                    notebooks=notebooks, tensorboards=tensorboards),
        pipeline_client=pipelines,
    )
    op.webui.metrics = op.metrics
    # recurring pipeline runs fire from the serving loop (scheduled-workflow
    # role; PipelineClient is self-locking and never touches job state) and
    # idle notebooks are culled under the operator lock (shared cluster)
    op.serving_tickers += (pipelines.tick,
                           lambda: op._locked(notebooks.cull_idle))
    tls_cert = tls_key = None
    if args.tls_dir:
        import ipaddress

        from kubeflow_tpu.platform.certs import ensure_self_signed

        hostnames, ips = ["localhost"], ["127.0.0.1", "0.0.0.0"]
        try:
            ipaddress.ip_address(args.bind_host)
            if args.bind_host not in ips:
                ips.append(args.bind_host)
        except ValueError:
            hostnames.append(args.bind_host)
        tls_cert, tls_key = ensure_self_signed(
            args.tls_dir, hostnames=hostnames, ip_sans=ips)
    port = op.start(port=args.port, host=args.bind_host,
                    tls_cert=tls_cert, tls_key=tls_key)
    if resumed:
        print(f"kft-operator resumed experiments: {resumed}", flush=True)
    if resumed_pipelines:
        print(f"kft-operator resumed pipelines: {resumed_pipelines}",
              flush=True)
    print(f"kft-operator serving on {args.bind_host}:{port}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    op.stop()
    if isinstance(cluster, LocalProcessCluster):
        cluster.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
