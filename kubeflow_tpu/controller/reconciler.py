"""Generic job reconciler — the heart of the training layer.

Mirrors the reference's common JobController capabilities (SURVEY.md §2.1:
ReconcileJobs/ReconcilePods/ReconcileServices/UpdateJobStatus + §3.1 call
stack): idempotent reconcile from desired spec to pods/services, gang
admission, per-kind rendezvous env injection, status aggregation, restart
with backoff, TTL cleanup. TPU-first differences:

- Rendezvous env is the jax.distributed contract (KFT_COORDINATOR /
  KFT_NUM_PROCESSES / KFT_PROCESS_ID + KFT_MESH topology), not
  MASTER_ADDR/NCCL (SURVEY.md §2.8). TF_CONFIG is still produced for the
  TFJob-compat kind.
- Failure domain is ELASTIC (per-worker replacement first, whole-gang
  restart as the counted fallback): when a worker dies and the cluster has
  warm capacity (``cluster.warm_pool``), the reconciler deletes ONLY the
  dead pod, stamps the replacement with the dead worker's rank/rendezvous
  env under a new worker-incarnation id (gang reservation and job uid
  preserved), and signals surviving pods to re-rendezvous in place —
  training resumes from the latest checkpoint at the exact step. The
  whole-slice gang restart (delete ALL pods, re-admit) remains for the
  cases where ICI/rendezvous structure really is lost: the coordinator
  (global rank 0 of a multi-process world) died, no standby is claimable,
  survivors cannot be restarted in place, or a worker exhausted its
  per-worker replacement budget. Both paths apply exponential backoff
  with jitter between attempts (counted, visible in job conditions).
"""

from __future__ import annotations

import json
import random
import time
import uuid
from typing import Optional

from kubeflow_tpu.api.types import (
    CleanPodPolicy, Condition, ConditionType, JobSpec, JobStatus, ReplicaStatus,
    ReplicaType, RestartPolicy, validate,
)
from kubeflow_tpu.controller.cluster import (
    Cluster, LocalProcessCluster, Pod, PodPhase, Service, admit_pod,
)
from kubeflow_tpu.controller.gang import GangScheduler, PodGroup

COORDINATOR_PORT = 8476


def pod_name(job: JobSpec, rtype: str, index: int) -> str:
    return f"{job.name}-{rtype.lower()}-{index}"


def _global_rank(job: JobSpec, rtype: str, index: int, anchor: str) -> int:
    """Global process id across ALL replica types, anchor type first then
    the rest in name order — so every kind forms one world with unique,
    stable ids (the SURVEY.md §2.8 pod-ordinal contract)."""
    order = sorted(job.replica_specs, key=lambda rt: (rt != anchor, rt))
    offset = 0
    for rt in order:
        if rt == rtype:
            break
        offset += job.replica_specs[rt].replicas
    return offset + index


def _job_selector(job: JobSpec) -> dict[str, str]:
    return {"job-name": job.name, "job-uid": job.uid}


def _pipeline_stages(job: JobSpec) -> int:
    """MPMD pipeline topology marker: a JAXJob whose Worker template
    carries KFT_NUM_STAGES is a pipeline gang — its workers split into
    per-stage groups, gang-scheduled as ONE job (one PodGroup, one
    all-or-nothing admission), each group its own jitted program wired
    to its neighbors by the stage rendezvous env below. 0 = not MPMD."""
    if job.kind != "JAXJob":
        return 0
    spec = job.replica_specs.get(ReplicaType.WORKER.value)
    if spec is None:
        return 0
    try:
        return int(spec.template.env.get("KFT_NUM_STAGES", "0"))
    except ValueError:
        return 0


def _stage_service_name(job: JobSpec, stage: int) -> str:
    return f"{job.name}-stage-{stage}"


class JobController:
    """Reconciles JobSpecs against a Cluster. Also plays the apiserver role:
    `submit`/`get`/`delete` mutate the job store, `reconcile` converges it."""

    def __init__(self, cluster: Cluster, scheduler: Optional[GangScheduler] = None,
                 pod_mutator=None, *,
                 restart_backoff_base_s: float = 1.0,
                 restart_backoff_cap_s: float = 60.0,
                 restart_backoff_jitter: float = 0.2):
        self.cluster = cluster
        self.scheduler = scheduler or GangScheduler()
        self.jobs: dict[tuple[str, str], JobSpec] = {}
        self.metrics: dict[str, float] = {}   # controller-level observability
        # restart/replacement pacing: attempt 1 requeues immediately (a
        # preempted host must not wait out a penalty it didn't earn),
        # attempt n >= 2 waits base * 2^(n-2) (capped, jittered) — a
        # crash-looping worker must not hammer the claim path
        self.restart_backoff_base_s = restart_backoff_base_s
        self.restart_backoff_cap_s = restart_backoff_cap_s
        self.restart_backoff_jitter = restart_backoff_jitter
        self._backoff_rng = random.Random()
        self._requeue_at: dict[tuple[str, str], float] = {}
        # replacement-in-flight fencing: FAILED pods whose delete is
        # already issued must not re-trigger _handle_failure while the
        # apiserver/informer catches up (idempotence under event-driven
        # reconcile); entries auto-expire so a stuck delete re-handles
        self._replacing: dict[tuple[str, str], dict[str, float]] = {}
        self._replace_grace_s = 30.0
        # recovery timeline per job — the bench decomposes
        # recovery_seconds (detect/claim/...) from these timestamps plus
        # the worker-side phase stamps
        self.recovery_log: dict[tuple[str, str], list[dict]] = {}
        # admission hook (PodDefaults registry / webhook equivalent)
        self.pod_mutator = pod_mutator
        # validating-admission hooks run on EVERY submission path (HTTP,
        # SDK, HPO trial jobs) — quota enforcement lives here, not in the
        # HTTP-facing wrapper, so nothing can route around it
        self.admission_checks: list = []
        # optional durable job-spec store (KubeCluster: JobCRStore — the
        # jobs live as CRs in the apiserver, the reference's etcd role);
        # submit/delete/condition changes write through it
        self.job_store = None

    # ---------------- apiserver-ish surface ----------------

    def submit(self, job: JobSpec) -> JobSpec:
        validate(job)
        key = (job.namespace, job.name)
        # existence before quota: a retried POST for a job that already
        # exists must report the collision, not a misleading 403
        if key in self.jobs:
            raise KeyError(f"job {key} already exists")
        for check in self.admission_checks:
            check(job)
        # ALWAYS server-generated (client YAML may echo an exported uid —
        # honoring it would let a resubmission adopt a dead incarnation's
        # terminal pods and "succeed" without running); restore() is the
        # only path that keeps a uid
        job.uid = uuid.uuid4().hex[:12]
        job.status = JobStatus()
        self._set_condition(job, ConditionType.CREATED, "JobCreated")
        job.status.start_time = time.time()
        self.jobs[key] = job
        # register the gang group at submission so a later admission cycle
        # sees all queued jobs and can order by priority, not arrival
        if job.run_policy.scheduling.gang and not job.run_policy.suspend:
            self._ensure_podgroup(job)
        if self.job_store is not None:
            self.job_store.save(job)
        return job

    def restore(self, job: JobSpec) -> JobSpec:
        """Re-adopt a job loaded from the durable store after a controller
        restart: no re-validation/quota (it was admitted once), uid kept so
        existing pods still match the job-uid selector, gang group
        re-registered for unfinished jobs."""
        key = (job.namespace, job.name)
        self.jobs[key] = job
        if (not job.status.is_finished()
                and job.run_policy.scheduling.gang
                and not job.run_policy.suspend):
            self._ensure_podgroup(job)
        return job

    def get(self, namespace: str, name: str) -> Optional[JobSpec]:
        return self.jobs.get((namespace, name))

    def delete(self, namespace: str, name: str) -> None:
        job = self.jobs.pop((namespace, name), None)
        if job:
            self._delete_pods(job)
            self._drop_bookkeeping(job)

    def forget(self, namespace: str, name: str) -> Optional[JobSpec]:
        """Remove a job from the controller WITHOUT deleting its pods —
        the warm-pool reclaim arc (hpo/swarm.py): an early-stopped
        trial's claimed pod goes back to the pool, so the job record must
        stop reconciling FIRST (a reconcile pass between un-labeling the
        pod and deleting the job would see a vanished worker and start
        elastic recovery), and its selector-driven pod cleanup must never
        run. The caller owns the leftover pods. Returns the forgotten
        JobSpec, or None."""
        job = self.jobs.pop((namespace, name), None)
        if job:
            self._drop_bookkeeping(job)
        return job

    def _drop_bookkeeping(self, job: JobSpec) -> None:
        namespace, name = job.namespace, job.name
        self.cluster.delete_service(namespace, job.name)
        for sid in range(_pipeline_stages(job)):
            self.cluster.delete_service(
                namespace, _stage_service_name(job, sid))
        self.scheduler.remove_group(namespace, job.name)
        self._requeue_at.pop((namespace, name), None)
        self._replacing.pop((namespace, name), None)
        self.recovery_log.pop((namespace, name), None)
        if self.job_store is not None:
            self.job_store.delete(job)

    # ---------------- reconcile ----------------

    def reconcile(self, namespace: str, name: str) -> Optional[JobSpec]:
        t0 = time.perf_counter()
        job = self.jobs.get((namespace, name))
        if job is None:
            return None
        if job.run_policy.suspend:
            self._set_condition(job, ConditionType.SUSPENDED, "JobSuspended")
            self._delete_pods(job)
            # release the gang reservation — a suspended job must not hold
            # slice capacity
            self.scheduler.remove_group(job.namespace, job.name)
            return job
        if job.status.is_finished():
            self._maybe_cleanup(job)
            return job

        self._ensure_service(job)
        # restart/replacement backoff gate: status keeps converging (a
        # finished survivor, a deadline) but no pods are (re)created until
        # the requeue clock expires — the anti-crash-loop pacing
        requeued = time.time() >= self._requeue_at.get(
            (namespace, name), 0.0)
        if requeued:
            if job.run_policy.scheduling.gang:
                self._ensure_podgroup(job)
                self.scheduler.try_admit()
            self._ensure_pods(job)
            self._start_admitted(job)
        self._update_status(job)
        self._check_deadline(job)
        self.metrics["reconcile_seconds"] = time.perf_counter() - t0
        return job

    def run_to_completion(
        self, namespace: str, name: str, timeout: float = 120.0, poll: float = 0.2
    ) -> JobSpec:
        """Poll-reconcile until the job finishes (local/e2e driver)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.reconcile(namespace, name)
            if job is None:
                raise KeyError(f"job {namespace}/{name} not found")
            if job.status.is_finished():
                return job
            time.sleep(poll)
        raise TimeoutError(f"job {namespace}/{name} did not finish in {timeout}s")

    # ---------------- internals ----------------

    def _ensure_service(self, job: JobSpec) -> None:
        if self.cluster.get_service(job.namespace, job.name) is None:
            self.cluster.create_service(Service(
                name=job.name, namespace=job.namespace,
                selector=_job_selector(job), port=COORDINATOR_PORT,
            ))
        # MPMD pipeline jobs: one service PER STAGE, so the stage
        # rendezvous env (KFT_STAGE_BIND / _PREV / _NEXT) resolves to an
        # address that is stable across per-worker replacement — a
        # replaced stage worker binds the same resolved endpoint and its
        # neighbors' env keeps pointing at it, no re-stamp needed
        for sid in range(_pipeline_stages(job)):
            sname = _stage_service_name(job, sid)
            if self.cluster.get_service(job.namespace, sname) is None:
                self.cluster.create_service(Service(
                    name=sname, namespace=job.namespace,
                    selector={**_job_selector(job),
                              "pipeline-stage": str(sid)},
                    port=COORDINATOR_PORT + 1 + sid,
                ))

    def _ensure_podgroup(self, job: JobSpec) -> None:
        sched = job.run_policy.scheduling
        accel = "any"
        for spec in job.replica_specs.values():
            if spec.template.tpu is not None:
                accel = spec.template.tpu.accelerator
        self.scheduler.add_group(
            PodGroup(
                name=job.name, namespace=job.namespace,
                min_member=sched.min_available or job.total_replicas,
                queue=sched.queue, priority=sched.priority,
            ),
            accelerator=accel,
        )

    def _ensure_pods(self, job: JobSpec) -> None:
        for rtype, spec in job.replica_specs.items():
            for i in range(spec.replicas):
                name = pod_name(job, rtype, i)
                if self.cluster.get_pod(job.namespace, name) is None:
                    env = self.cluster_env(job, rtype, i)
                    env.update(spec.template.env)
                    # worker-incarnation stamp: a replacement pod carries
                    # the dead worker's rank env (computed above — same
                    # KFT_PROCESS_ID) plus its incarnation id and the
                    # job's rendezvous epoch, so the worker can tell a
                    # fresh start from a mid-job takeover and every
                    # member of the re-formed world agrees on the epoch
                    if job.status.rendezvous_epoch:
                        env["KFT_WORKER_INCARNATION"] = str(
                            job.status.replacement_counts.get(name, 0))
                        env["KFT_RENDEZVOUS_EPOCH"] = str(
                            job.status.rendezvous_epoch)
                    tpu = spec.template.tpu
                    labels = {**_job_selector(job), "replica-type": rtype,
                              "replica-index": str(i)}
                    if "KFT_STAGE_ID" in env:
                        # stage selector for the per-stage service (and
                        # anything else that addresses one stage's group)
                        labels["pipeline-stage"] = env["KFT_STAGE_ID"]
                    pod = Pod(
                        name=name, namespace=job.namespace,
                        labels=labels,
                        env=env,
                        command=list(spec.template.command),
                        image=spec.template.image,
                        # GKE TPU scheduling contract (BASELINE.md): slice
                        # topology selectors + google.com/tpu, never GPUs
                        node_selector={
                            "cloud.google.com/gke-tpu-accelerator":
                                f"tpu-{tpu.accelerator}",
                            "cloud.google.com/gke-tpu-topology":
                                tpu.topology,
                        } if tpu is not None else {},
                        resources={
                            "google.com/tpu": str(tpu.chips_per_host),
                        } if tpu is not None else {},
                        # job pods are gang-gated on real backends until
                        # _start_admitted lifts the gate (the gate also
                        # latches late-bound env like KFT_SLICE_ID); this
                        # covers non-gang jobs too — admission happens in
                        # the same reconcile pass, and the latch guarantees
                        # the env annotations land before the container runs
                        gang=True,
                    )
                    if self.pod_mutator is not None:
                        pod = self.pod_mutator(pod)
                    try:
                        self.cluster.create_pod(pod)
                    except KeyError:
                        # lost a create race (event-driven reconcile can
                        # overlap an API-thread reconcile; on kube, a
                        # lagging informer can also briefly hide a live
                        # pod): the pod exists — adopt it next read
                        continue

    def _start_admitted(self, job: JobSpec) -> None:
        admitted = (
            not job.run_policy.scheduling.gang
            or self.scheduler.is_admitted(job.namespace, job.name)
        )
        if not admitted:
            return
        # placement hint: fill the reserved slices host by host with the
        # TPU-bearing replicas in (type, index) order (the GKE nodeSelector
        # role — each worker learns which physical slice it runs on).
        # Replicas whose template requests no TPU (e.g. a coordinator) get
        # no slice assignment.
        alloc = self.scheduler.slice_allocation(job.namespace, job.name)
        pods = self.cluster.list_pods(job.namespace, _job_selector(job))
        if alloc:
            tpu_types = {rt for rt, spec in job.replica_specs.items()
                         if spec.template.tpu is not None} or set(
                             job.replica_specs)
            tpu_pods = sorted(
                (p for p in pods if p.labels.get("replica-type") in tpu_types),
                key=lambda p: (p.labels.get("replica-type", ""),
                               int(p.labels.get("replica-index", 0))))
            flat = [sid for sid, hosts in alloc for _ in range(hosts)]
            for pod, sid in zip(tpu_pods, flat):
                pod.env.setdefault("KFT_SLICE_ID", sid)
        for pod in pods:
            if pod.phase == PodPhase.PENDING and not pod.scheduled:
                # backend's admission hook: LocalProcessCluster launches the
                # process; KubeCluster lifts the scheduling gate + publishes
                # late-bound env; FakeCluster has none (tests play kubelet)
                admit_pod(self.cluster, pod)

    def cluster_env(self, job: JobSpec, rtype: str, index: int) -> dict[str, str]:
        """Per-kind rendezvous env (the reference's SetClusterSpec equivalent)."""
        coordinator = self.cluster.resolve(job.namespace, job.name)
        if job.kind == "JAXJob":
            env = {
                "KFT_COORDINATOR": coordinator,
                "KFT_NUM_PROCESSES": str(job.total_replicas),
                "KFT_PROCESS_ID": str(_global_rank(
                    job, rtype, index, ReplicaType.COORDINATOR.value)),
                "KFT_JOB_NAME": job.name,
                "KFT_REPLICA_TYPE": rtype,
                "TPU_WORKER_ID": str(index),
            }
            spec = job.replica_specs[rtype]
            stages = _pipeline_stages(job)
            if stages > 1 and rtype == ReplicaType.WORKER.value:
                # MPMD stage rendezvous (parallel/mpmd.py): workers split
                # into contiguous per-stage groups; each learns its stage,
                # its own stable listen address, and its neighbors' — the
                # point-to-point activation/grad links. Stage workers do
                # NOT form one jax.distributed world (each stage is its
                # own program on its own mesh), so KFT_NUM_PROCESSES etc.
                # above stay purely informational for them.
                wps = max(1, spec.replicas // stages)
                sid = min(index // wps, stages - 1)
                env["KFT_NUM_STAGES"] = str(stages)
                env["KFT_STAGE_ID"] = str(sid)
                env["KFT_STAGE_WORKERS"] = str(wps)
                env["KFT_STAGE_PROC_ID"] = str(index % wps)
                # per-stage worker-group identity: the rendezvous triplet
                # of the (future) per-stage jax.distributed world. Rank 0
                # of each group is that world's coordinator, addressed by
                # the stage service.
                env["KFT_STAGE_GROUP_SIZE"] = str(wps)
                env["KFT_STAGE_GROUP_RANK"] = str(index % wps)
                env["KFT_STAGE_GROUP_COORD"] = self.cluster.resolve(
                    job.namespace, _stage_service_name(job, sid))
                env["KFT_STAGE_BIND"] = self.cluster.resolve(
                    job.namespace, _stage_service_name(job, sid))
                if sid > 0:
                    env["KFT_STAGE_PREV"] = self.cluster.resolve(
                        job.namespace, _stage_service_name(job, sid - 1))
                if sid < stages - 1:
                    env["KFT_STAGE_NEXT"] = self.cluster.resolve(
                        job.namespace, _stage_service_name(job, sid + 1))
                # interleaved-1F1B: when the template asks for V>1 virtual
                # stages the chunk graph wraps around the worker ring —
                # the last stage forwards activations to stage 0's next
                # chunk, and stage 0 returns grads to the last stage.
                vstages = int(spec.template.env.get("KFT_VIRTUAL_STAGES", "1"))
                if vstages > 1:
                    env["KFT_VIRTUAL_STAGES"] = str(vstages)
                    if sid == stages - 1:
                        env["KFT_STAGE_WRAP_NEXT"] = self.cluster.resolve(
                            job.namespace, _stage_service_name(job, 0))
                    if sid == 0:
                        env["KFT_STAGE_WRAP_PREV"] = self.cluster.resolve(
                            job.namespace, _stage_service_name(job, stages - 1))
            if spec.template.tpu is not None:
                tpu = spec.template.tpu
                env["KFT_TPU_ACCELERATOR"] = tpu.accelerator
                env["KFT_TPU_TOPOLOGY"] = tpu.topology
                # topology discovery (SURVEY.md §2.8): when the user gave no
                # explicit mesh, derive one from the slice topology — fsdp
                # over the slice's chips (ZeRO-3 default), DCN data across
                # slices when the job spans several (gke-tpu-topology label
                # -> mesh, without hand-written KFT_MESH)
                if "KFT_MESH" not in spec.template.env:
                    # size the mesh by the devices the job ACTUALLY has
                    # (replicas x chips/host), not the slice type's full
                    # chip count — a partial-slice job must still boot
                    hosts_per_slice = max(1, tpu.num_hosts)
                    w = spec.replicas
                    if w > hosts_per_slice and w % hosts_per_slice == 0:
                        # regular multislice: DCN data across slices
                        env.setdefault(
                            "KFT_MESH", f"fsdp={tpu.num_chips}")
                        env.setdefault(
                            "KFT_DCN", f"data={w // hosts_per_slice}")
                    else:
                        env.setdefault(
                            "KFT_MESH",
                            f"fsdp={w * tpu.chips_per_host}")
            return env
        if job.kind in ("PyTorchJob", "XGBoostJob"):
            # torch.distributed / XGBoost-Rabit contract (reference
            # SetClusterSpec: pkg/controller.v1/{pytorch,xgboost}/envvar).
            # Rank 0 is the Master (tracker/store host); global ranks are
            # Master-first then Workers in (type, index) order.
            host, _, port = coordinator.rpartition(":")
            env = {
                "MASTER_ADDR": host,
                "MASTER_PORT": port,
                "WORLD_SIZE": str(job.total_replicas),
                "RANK": str(_global_rank(
                    job, rtype, index, ReplicaType.MASTER.value)),
            }
            if job.kind == "XGBoostJob":
                # Rabit tracker flavor: the tracker runs on the Master and
                # workers learn their count via WORLD_SIZE. WORKER_PORT must
                # be unique per worker on a shared host (LocalProcessCluster);
                # with per-pod IPs the fixed convention port suffices.
                alloc = getattr(self.cluster, "allocate_port", None)
                env["WORKER_PORT"] = str(
                    alloc() if alloc else COORDINATOR_PORT + 1)
            elif job.elastic is not None:
                # torchrun c10d elastic rendezvous (PET_* is torchrun's
                # documented env surface)
                e = job.elastic
                env.update({
                    "PET_RDZV_BACKEND": e.rdzv_backend,
                    "PET_RDZV_ENDPOINT": coordinator,
                    "PET_NNODES": f"{e.min_replicas}:{e.max_replicas}",
                    "PET_NPROC_PER_NODE": str(e.nproc_per_node),
                    "PET_MAX_RESTARTS": str(e.max_restarts),
                })
            return env
        if job.kind == "TFJob":
            cluster: dict[str, list[str]] = {}
            for rt, spec in job.replica_specs.items():
                hosts = [
                    f"{pod_name(job, rt, i)}.{job.namespace}.svc:2222"
                    for i in range(spec.replicas)
                ]
                cluster[rt.lower()] = hosts
            tf_config = {
                "cluster": cluster,
                "task": {"type": rtype.lower(), "index": index},
            }
            return {"TF_CONFIG": json.dumps(tf_config)}
        return {"KFT_COORDINATOR": coordinator}

    def _update_status(self, job: JobSpec) -> None:
        pods = self.cluster.list_pods(job.namespace, _job_selector(job))
        key = (job.namespace, job.name)
        # purge replacement fences whose pod vanished (delete landed) or
        # whose delete has been in flight too long (re-handle, never wedge)
        fences = self._replacing.get(key)
        if fences:
            now = time.time()
            failed_by_name = {p.name: p for p in pods if p is not None
                              and p.phase == PodPhase.FAILED}
            for n, (t, expect_inc) in list(fences.items()):
                p = failed_by_name.get(n)
                # drop the fence when the fenced pod vanished (delete
                # landed), when the delete has been in flight too long
                # (never wedge), or when the FAILED pod under this name
                # already carries the NEW incarnation id — the replacement
                # itself died, a second failure mid-recovery that must be
                # re-handled, not masked. (A lagging informer replay of
                # the OLD pod carries the old incarnation env and stays
                # fenced — replacement is never double-fired for one
                # death.)
                try:
                    inc = int((p.env if p is not None else {}).get(
                        "KFT_WORKER_INCARNATION", -1))
                except (TypeError, ValueError):
                    inc = -1
                if (p is None or inc >= expect_inc
                        or now - t > self._replace_grace_s):
                    fences.pop(n, None)
        stats: dict[str, ReplicaStatus] = {}
        for rtype in job.replica_specs:
            stats[rtype] = ReplicaStatus()
        any_failed = False
        for pod in pods:
            if pod is None:
                continue
            rtype = pod.labels.get("replica-type", "")
            rs = stats.setdefault(rtype, ReplicaStatus())
            if pod.phase == PodPhase.RUNNING:
                rs.active += 1
            elif pod.phase == PodPhase.SUCCEEDED:
                rs.succeeded += 1
            elif pod.phase == PodPhase.FAILED:
                rs.failed += 1
                # a pod already being replaced (delete issued, apiserver /
                # informer lag still shows it) must not re-trigger failure
                # handling — the fence keeps replacement idempotent
                if pod.name not in self._replacing.get(key, {}):
                    any_failed = True
        job.status.replica_statuses = stats

        success_rtype, success_index = self._success_anchor(job)
        anchor = next(
            (p for p in pods if p is not None
             and p.labels.get("replica-type") == success_rtype
             and p.labels.get("replica-index") == str(success_index)),
            None,
        )

        if any_failed:
            self._handle_failure(job, pods)
            return
        if anchor is not None and anchor.phase == PodPhase.SUCCEEDED:
            self._set_condition(job, ConditionType.SUCCEEDED, "JobSucceeded")
            job.status.completion_time = time.time()
            self._maybe_cleanup(job)
            return
        total_active = sum(rs.active for rs in stats.values())
        if total_active == job.total_replicas:
            self._set_condition(job, ConditionType.RUNNING, "JobRunning")

    def _success_anchor(self, job: JobSpec) -> tuple[str, int]:
        """Replica whose success marks job success (reference: chief/worker-0)."""
        for rt in (ReplicaType.CHIEF.value, ReplicaType.MASTER.value,
                   ReplicaType.COORDINATOR.value, ReplicaType.WORKER.value):
            if rt in job.replica_specs:
                return rt, 0
        return next(iter(job.replica_specs)), 0

    def _handle_failure(self, job: JobSpec, pods: list) -> None:
        key = (job.namespace, job.name)
        failed = [p for p in pods if p is not None
                  and p.phase == PodPhase.FAILED
                  and p.name not in self._replacing.get(key, {})]
        if not failed:
            return
        policy = self._restart_policy(job)
        retryable = policy in (RestartPolicy.ON_FAILURE, RestartPolicy.ALWAYS,
                               RestartPolicy.EXIT_CODE)
        if policy == RestartPolicy.EXIT_CODE:
            # k8s convention: 128+N = killed by signal N. Local Popen reports
            # signal deaths as negative returncodes — both are retryable.
            retryable = any(
                (p.exit_code or 0) >= 128 or (p.exit_code or 0) < 0
                for p in failed
            )
        now = time.time()
        for p in failed:
            # detection timestamp: the first reconcile that OBSERVES the
            # failure — the bench's detect phase ends here
            self._log_recovery(job, "worker_failed", pod=p.name,
                               exit_code=p.exit_code, t=now)
        if retryable and self._try_replacement(job, failed, pods):
            return
        if retryable and job.status.restart_count < job.run_policy.backoff_limit:
            job.status.restart_count += 1
            delay = self._arm_backoff(job, job.status.restart_count)
            self._set_condition(
                job, ConditionType.RESTARTING,
                f"GangRestart#{job.status.restart_count}",
                "worker failure => whole-slice restart "
                f"(no per-worker replacement possible); backoff {delay:.1f}s",
            )
            self._log_recovery(job, "gang_restart",
                               count=job.status.restart_count,
                               backoff_s=round(delay, 3))
            self.metrics["gang_restarts_total"] = (
                self.metrics.get("gang_restarts_total", 0) + 1)
            # gang restart: tear down everything, drop the reservation,
            # requeue; the whole gang re-forms, so per-worker replacement
            # budgets reset with it (the epoch does NOT — any straggler
            # from the old world must see a newer epoch, never its own)
            job.status.rendezvous_epoch += 1
            job.status.replacement_counts.clear()
            self._replacing.pop(key, None)
            self._delete_pods(job)
            self.scheduler.remove_group(job.namespace, job.name)
        else:
            self._set_condition(job, ConditionType.FAILED, "BackoffLimitExceeded")
            job.status.completion_time = time.time()
            self._maybe_cleanup(job)

    # ---------------- elastic per-worker replacement ----------------

    def _pod_identity(self, job: JobSpec, pod) -> str:
        """The job pod identity a cluster pod serves — on the kube backend
        a claimed warm-pool standby keeps its own name, so identity comes
        from the replica labels (the per-worker budget must follow the
        RANK, not whichever standby happened to serve it)."""
        rtype = pod.labels.get("replica-type")
        idx = pod.labels.get("replica-index")
        if rtype is not None and idx is not None:
            return pod_name(job, rtype, int(idx))
        return pod.name

    def _try_replacement(self, job: JobSpec, failed: list,
                         pods: list) -> bool:
        """Per-worker warm replacement: delete ONLY the dead pods, keep the
        gang reservation and job uid, recreate the dead ranks under a new
        worker-incarnation id, and signal survivors to re-rendezvous in
        place. Returns False (caller falls back to the counted gang
        restart) when the composition cannot hold: no warm capacity, the
        coordinator died, a worker exhausted its replacement budget, no
        standby is claimable, or a survivor cannot be restarted in place."""
        key = (job.namespace, job.name)
        pool = getattr(self.cluster, "warm_pool", None)
        if not pool:
            return False            # no warm capacity: gang restart
        # the coordinator (global rank 0) hosts the jax.distributed
        # rendezvous service of a multi-process world — its death takes
        # the world's anchor with it; single-process jobs have no
        # coordinator service, so any rank is replaceable
        if job.total_replicas > 1:
            for p in failed:
                rtype = p.labels.get("replica-type", "")
                idx = int(p.labels.get("replica-index", 0) or 0)
                if rtype in job.replica_specs and _global_rank(
                        job, rtype, idx,
                        ReplicaType.COORDINATOR.value) == 0:
                    self._log_recovery(job, "replacement_refused",
                                       reason="coordinator_died")
                    return False
        # per-worker budget (backoff accounting per worker): a rank that
        # keeps dying burns ITS budget, not the job's gang-restart budget
        limit = job.run_policy.backoff_limit
        idents = {p.name: self._pod_identity(job, p) for p in failed}
        for ident in idents.values():
            if job.status.replacement_counts.get(ident, 0) >= limit:
                self._log_recovery(job, "replacement_refused",
                                   reason="worker_budget_exhausted",
                                   pod=ident)
                return False
        # a real pool (WarmPoolController) must have a claimable standby,
        # or the replacement would cold-start — worse than the gang
        # restart it was supposed to beat; truthy warm_pool without
        # standby accounting (LocalProcessCluster zygote) is always warm
        if hasattr(pool, "standby_count"):
            cls = self._pool_class(job)
            avail = (pool.claimable(cls) if hasattr(pool, "claimable")
                     else pool.standby_count(cls))
            if avail < len(failed):
                self._log_recovery(job, "replacement_refused",
                                   reason="no_claimable_standby")
                return False
        # survivors must be re-rendezvous-able in place (kill + respawn
        # the process INSIDE the pod: pod identity, claim, node-local
        # caches all survive); a backend or pod that can't do that forces
        # the gang path
        survivors = [p for p in pods if p is not None
                     and p.phase == PodPhase.RUNNING]
        restart = getattr(self.cluster, "restart_pod_process", None)
        if survivors and _pipeline_stages(job) <= 1:
            # pipeline survivors reform in process (no restart needed —
            # see the commit block), so only the SPMD path requires the
            # backend to support in-place process restarts
            if restart is None:
                self._log_recovery(job, "replacement_refused",
                                   reason="no_in_place_restart")
                return False
            can = getattr(self.cluster, "can_restart_in_place",
                          lambda pod: True)
            if not all(can(p) for p in survivors):
                self._log_recovery(job, "replacement_refused",
                                   reason="survivor_not_restartable")
                return False
        # ---- commit ----
        job.status.rendezvous_epoch += 1
        epoch = job.status.rendezvous_epoch
        if _pipeline_stages(job) > 1:
            # MPMD pipeline stages reform IN PROCESS (parallel/mpmd.py
            # elastic contract): the replacement pod boots with the
            # bumped epoch and announces it through the shared snapshot
            # dir; survivors' epoch watchers poison the in-flight
            # microbatch window, restore the last common step boundary,
            # and re-rendezvous on the same stage-Service addresses —
            # keeping their compiled programs and params hot instead of
            # paying a process restart + recompile per survivor.
            for p in survivors:
                self._log_recovery(job, "survivor_reform_signaled",
                                   pod=p.name, epoch=epoch)
        else:
            # survivors re-rendezvous in place under the new epoch FIRST
            # — their pods (claims, node-local caches) are NOT deleted.
            # A signal that fails to deliver leaves that survivor wedged
            # in the old world, so the whole attempt falls back to the
            # counted gang restart (which tears every member down
            # uniformly); the epoch bump stands — the gang path bumps
            # past it again.
            for p in survivors:
                try:
                    ok = restart(p.namespace, p.name,
                                 {"KFT_RENDEZVOUS_EPOCH": str(epoch)})
                except Exception:
                    ok = False
                self._log_recovery(job, "survivor_restarted", pod=p.name,
                                   ok=bool(ok))
                if not ok:
                    self._log_recovery(job, "replacement_refused",
                                       reason="survivor_restart_failed",
                                       pod=p.name)
                    return False
        attempt = 0
        for p in failed:
            ident = idents[p.name]
            n = job.status.replacement_counts.get(ident, 0) + 1
            job.status.replacement_counts[ident] = n
            attempt = max(attempt, n)
            job.status.worker_replacements += 1
            self._replacing.setdefault(key, {})[p.name] = (time.time(), n)
            try:
                self.cluster.delete_pod(job.namespace, p.name)
            except Exception:
                pass        # fence expiry re-handles a stuck delete
            self._log_recovery(job, "replacement", pod=ident, via=p.name,
                               incarnation=n, epoch=epoch)
        self.metrics["worker_replacements_total"] = (
            self.metrics.get("worker_replacements_total", 0) + len(failed))
        delay = self._arm_backoff(job, attempt)
        self._set_condition(
            job, ConditionType.RESTARTING,
            f"WorkerReplacement#{job.status.worker_replacements}",
            f"warm per-worker replacement of {sorted(idents.values())} "
            f"(epoch {epoch}, gang preserved); backoff {delay:.1f}s",
        )
        return True

    def _pool_class(self, job: JobSpec) -> Optional[str]:
        for spec in job.replica_specs.values():
            if spec.template.tpu is not None:
                return spec.template.tpu.accelerator
        return None

    def _arm_backoff(self, job: JobSpec, attempt: int) -> float:
        """Exponential backoff with jitter between restart/replacement
        attempts: attempt 1 requeues immediately, attempt n waits
        base * 2^(n-2) (capped), +/- jitter. Returns the armed delay."""
        if attempt <= 1 or self.restart_backoff_base_s <= 0:
            delay = 0.0
        else:
            delay = min(self.restart_backoff_cap_s,
                        self.restart_backoff_base_s * 2 ** (attempt - 2))
            if self.restart_backoff_jitter:
                delay *= 1 + self.restart_backoff_jitter * (
                    2 * self._backoff_rng.random() - 1)
        self._requeue_at[(job.namespace, job.name)] = time.time() + delay
        self.metrics["restart_backoff_seconds"] = delay
        return delay

    def _log_recovery(self, job: JobSpec, event: str,
                      t: Optional[float] = None, **fields) -> None:
        log = self.recovery_log.setdefault((job.namespace, job.name), [])
        log.append({"t": t if t is not None else time.time(),
                    "event": event, **fields})
        del log[:-200]          # bounded per job

    def _restart_policy(self, job: JobSpec) -> RestartPolicy:
        w = job.replica_specs.get(ReplicaType.WORKER.value)
        return w.restart_policy if w else RestartPolicy.NEVER

    def _check_deadline(self, job: JobSpec) -> None:
        if job.status.is_finished():
            return   # a finished (e.g. just-succeeded) job can't miss a deadline
        deadline = job.run_policy.active_deadline_seconds
        if deadline and job.status.start_time:
            if time.time() - job.status.start_time > deadline:
                self._set_condition(job, ConditionType.FAILED, "DeadlineExceeded")
                job.status.completion_time = time.time()
                self._delete_pods(job)

    def _maybe_cleanup(self, job: JobSpec) -> None:
        policy = job.run_policy.clean_pod_policy
        if policy == CleanPodPolicy.ALL:
            self._delete_pods(job)
        elif policy == CleanPodPolicy.RUNNING:
            for pod in self.cluster.list_pods(job.namespace, _job_selector(job)):
                if pod is not None and pod.phase == PodPhase.RUNNING:
                    self.cluster.delete_pod(job.namespace, pod.name)
        ttl = job.run_policy.ttl_seconds_after_finished
        if ttl is not None and job.status.completion_time:
            if time.time() - job.status.completion_time > ttl:
                self.delete(job.namespace, job.name)

    def _delete_pods(self, job: JobSpec) -> None:
        for pod in list(self.cluster.list_pods(job.namespace, _job_selector(job))):
            if pod is not None:
                self.cluster.delete_pod(job.namespace, pod.name)

    def _set_condition(
        self, job: JobSpec, ctype: ConditionType, reason: str = "", message: str = ""
    ) -> None:
        if job.status.condition() == ctype:
            return
        job.status.conditions.append(
            Condition(type=ctype, reason=reason, message=message)
        )
        if self.job_store is not None and (job.namespace, job.name) in self.jobs:
            # status write-through (the CR status-subresource role) so a
            # restarted controller never re-runs a finished job
            try:
                self.job_store.save(job)
            except Exception:
                pass      # durable status is best-effort; pods are truth
