"""`KubeCluster` — the Cluster backend that speaks to a real Kubernetes
apiserver over its REST API.

Parity: the reference's controllers ARE Kubernetes clients — client-go
informers + a workqueue reconciling Pods/Services ([U] training-operator:
pkg/controller.v1/common/{job.go,pod.go,service.go}; SURVEY.md §3.1). This
module plays that role for the `JobController`: the SAME reconcile logic
that drives FakeCluster/LocalProcessCluster drives a live apiserver through
this class, and the envtest-equivalent harness
(`controller/fake_apiserver.py`) proves it without a cluster.

Design (informer-cache, not request-per-read):

- The controller reads and MUTATES `Pod` dataclasses (env late-binding,
  `scheduled` flags, heartbeat-declared failures). KubeCluster keeps one
  dataclass per live pod — the informer-cache role — and `sync()`s status
  from the apiserver on reads, while local *writes* flow back explicitly:
  `create_pod` POSTs the manifest, `start_pod` (gang admission) PATCHes
  away the scheduling gate and publishes late-bound env as annotations.
- Gang admission maps to **pod scheduling gates**: pods are created with
  `schedulingGates: [{name: "kubeflow-tpu.org/gang"}]`, so a real
  kube-scheduler cannot place any member early; `start_pod` lifts the gate
  once the whole slice group is admitted. This is the K8s-native form of
  the whole-slice atom (SURVEY.md §2.1 gang glue).
- Phase merging is **terminal-wins**: once a pod is terminal locally (a
  heartbeat-declared failure) or remotely (kubelet truth), later syncs
  never resurrect it — mirrors pod-phase monotonicity.
- Late-bound values (e.g. KFT_SLICE_ID, decided at admission, after pod
  creation) cannot be env on an immutable pod spec; they publish as
  `kubeflow-tpu.org/env.<KEY>` annotations, surfaced in-container via a
  downward-API `podinfo` volume (`rendezvous.bootstrap` reads both).

No kubernetes client library: auth is a bearer token (+ CA bundle for
https), exactly what a ServiceAccount mount provides in-cluster.
"""

from __future__ import annotations

import http.client
import json
import os
import ssl
import threading
import time
from typing import Iterator, Optional
from urllib.parse import quote, urlparse

from kubeflow_tpu.controller.cluster import Pod, PodPhase, Service

GANG_GATE = "kubeflow-tpu.org/gang"
ENV_ANNOTATION_PREFIX = "kubeflow-tpu.org/env."
# elastic recovery: bumping this annotation tells the node agent to kill
# and respawn the pod's process IN PLACE (the survivor re-rendezvous
# signal) — the pod itself, its claim, and its node-local caches survive
RESTART_EPOCH_ANNOTATION = "kubeflow-tpu.org/restart-epoch"
# a claimed warm-pool standby pod records WHICH job pod identity it serves
# (controller/warmpool.py): a restarted controller rebuilds its name-alias
# map from this annotation alone
CLAIMED_AS_ANNOTATION = "kubeflow-tpu.org/claimed-as"
_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_PHASES = {
    "Pending": PodPhase.PENDING,
    "Running": PodPhase.RUNNING,
    "Succeeded": PodPhase.SUCCEEDED,
    "Failed": PodPhase.FAILED,
}
_TERMINAL = (PodPhase.SUCCEEDED, PodPhase.FAILED)

# apiVersion/kind -> (path prefix, plural) for generic apply()
_KIND_PATHS = {
    ("v1", "Pod"): "pods",
    ("v1", "Service"): "services",
    ("v1", "Namespace"): "namespaces",
    ("v1", "ConfigMap"): "configmaps",
    ("v1", "ServiceAccount"): "serviceaccounts",
    ("v1", "PersistentVolumeClaim"): "persistentvolumeclaims",
    ("apps/v1", "Deployment"): "deployments",
    ("rbac.authorization.k8s.io/v1", "ClusterRole"): "clusterroles",
    ("rbac.authorization.k8s.io/v1", "ClusterRoleBinding"):
        "clusterrolebindings",
    ("networking.k8s.io/v1", "NetworkPolicy"): "networkpolicies",
    ("apiextensions.k8s.io/v1", "CustomResourceDefinition"):
        "customresourcedefinitions",
}
_CLUSTER_SCOPED = {"Namespace", "ClusterRole", "ClusterRoleBinding",
                   "CustomResourceDefinition"}


class KubeApiError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"apiserver {code}: {message}")
        self.code = code


def pod_to_manifest(pod: Pod, image: str) -> dict:
    """Render the repo's Pod dataclass as a v1 Pod manifest. TPU placement
    travels as the GKE topology nodeSelector + google.com/tpu resource
    (BASELINE.md scheduling contract; platform/manifests.py is the shared
    convention)."""
    container = {
        "name": "worker",
        "image": pod.image or image,
        "env": [{"name": k, "value": str(v)}
                for k, v in sorted(pod.env.items())],
        "volumeMounts": [{"name": "podinfo", "mountPath": "/etc/podinfo"}],
    }
    if pod.resources:
        container["resources"] = {"limits": dict(pod.resources),
                                  "requests": dict(pod.resources)}
    if pod.command:
        container["command"] = list(pod.command)
    spec = {
        "restartPolicy": "Never",      # restarts are the controller's call
        "containers": [container],
        # late-bound admission values surface in-container through the
        # downward API (annotations stay mutable; pod env does not)
        "volumes": [{"name": "podinfo", "downwardAPI": {"items": [
            {"path": "annotations",
             "fieldRef": {"fieldPath": "metadata.annotations"}}]}}],
    }
    if pod.gang:
        # only gang-scheduled (job) pods are gated: the kube-scheduler must
        # not place any slice member before the whole group is admitted, and
        # the gate doubles as the late-bound-env latch (KFT_SLICE_ID lands
        # as annotations before the container can start). Serving/notebook
        # pods schedule individually and immediately.
        spec["schedulingGates"] = [{"name": GANG_GATE}]
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if pod.init_command:
        spec["initContainers"] = [{
            "name": "storage-initializer",
            "image": pod.image or image,
            "command": list(pod.init_command),
            "env": container["env"],
        }]
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": pod.name, "namespace": pod.namespace,
            "labels": dict(pod.labels),
            "annotations": {},
        },
        "spec": spec,
    }


def _manifest_status(doc: dict) -> tuple[PodPhase, Optional[int]]:
    status = doc.get("status", {}) or {}
    phase = _PHASES.get(status.get("phase", "Pending"), PodPhase.PENDING)
    exit_code = None
    for cs in status.get("containerStatuses", []) or []:
        term = (cs.get("state", {}) or {}).get("terminated")
        if term is not None and term.get("exitCode") is not None:
            exit_code = int(term["exitCode"])
    if exit_code is None and status.get("exitCode") is not None:
        exit_code = int(status["exitCode"])
    return phase, exit_code


class KubeCluster:
    """Cluster protocol over the Kubernetes REST API.

    ``base_url``: apiserver endpoint (e.g. https://10.0.0.1:443 or the
    fake apiserver's http URL). ``token``/``ca_file`` default to the
    in-cluster ServiceAccount mount when present.
    """

    def __init__(self, base_url: str, *, token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 insecure_skip_verify: bool = False,
                 image: str = "kubeflow-tpu/runtime:latest",
                 request_timeout: float = 30.0,
                 host_ports: bool = False):
        u = urlparse(base_url)
        self.scheme = u.scheme or "http"
        self.host = u.hostname
        self.port = u.port or (443 if self.scheme == "https" else 80)
        self.image = image
        self.timeout = request_timeout
        if host_ports:
            # image-less single-host mode (FakeKubelet runs every pod on
            # THIS machine): expose the allocate_port hook so per-pod
            # binds (serving KFT_BIND) get distinct loopback ports — the
            # pod-IP analogue. Real clusters keep container ports.
            from kubeflow_tpu.controller.cluster import _free_port

            self.allocate_port = _free_port
        if token is None and os.path.exists(f"{_SA_DIR}/token"):
            with open(f"{_SA_DIR}/token") as f:
                token = f.read().strip()
        if ca_file is None and os.path.exists(f"{_SA_DIR}/ca.crt"):
            ca_file = f"{_SA_DIR}/ca.crt"
        self.token = token
        self._ssl = None
        if self.scheme == "https":
            self._ssl = ssl.create_default_context(cafile=ca_file)
            if insecure_skip_verify:
                self._ssl.check_hostname = False
                self._ssl.verify_mode = ssl.CERT_NONE
        self._lock = threading.RLock()
        self._pods: dict[tuple[str, str], Pod] = {}     # informer cache
        self._gated: set[tuple[str, str]] = set()       # gate still set
        self._pushed_env: dict[tuple[str, str], dict] = {}
        self._services: dict[tuple[str, str], Service] = {}
        self._informer: Optional[threading.Thread] = None
        self._informer_stop = threading.Event()
        # informer-cache mode (the client-go architecture): while a
        # selector-free informer runs, get_pod/list_pods serve from the
        # watch-fed cache — zero REST requests between pod events; the
        # informer thread itself repairs drift with a periodic resync LIST
        self._cache_serving = False
        self._cache_namespace = ""          # "" = cluster-wide
        # called (event_type, pod) after each folded watch event — the
        # daemon hangs its reconcile wakeup here. on_pod_event is the
        # legacy single-callback slot; add_pod_event_listener supports
        # several subscribers (two Operators sharing one KubeCluster must
        # not silently detach each other — ADVICE r5 #1)
        self.on_pod_event = None
        self._pod_event_subs: list = []
        # warm-pool subsystem (controller/warmpool.py), attached by the
        # operator: start_pod claims a pre-warmed standby pod instead of
        # scheduling the cold one, and _claims maps the job pod NAME to
        # the standby pod actually serving it (k8s pods cannot be renamed)
        self.warm_pool = None
        self._claims: dict[tuple[str, str], tuple[str, str]] = {}

    # ------------------------------------------------------------ http --

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json") -> dict:
        if self.scheme == "https":
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=self.timeout,
                context=self._ssl)
        else:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        try:
            headers = {"Accept": "application/json"}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            data = None
            if body is not None:
                data = json.dumps(body).encode()
                headers["Content-Type"] = content_type
            conn.request(method, path, body=data, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status == 404:
                raise KubeApiError(404, path)
            if resp.status >= 400:
                try:
                    msg = json.loads(raw).get("message", raw.decode())
                except Exception:
                    msg = raw.decode(errors="replace")
                raise KubeApiError(resp.status, msg)
            return json.loads(raw) if raw else {}
        finally:
            conn.close()

    @staticmethod
    def _pod_path(ns: str, name: str = "", sub: str = "") -> str:
        # ns "" = cluster scope (/api/v1/pods): the informer's all-namespace
        # list+watch; named-pod verbs always carry a namespace
        p = (f"/api/v1/namespaces/{quote(ns)}/pods" if ns
             else "/api/v1/pods")
        if name:
            p += f"/{quote(name)}"
        if sub:
            p += f"/{sub}"
        return p

    # ------------------------------------------------------ pod verbs --

    def _claim_eligible(self, pod: Pod) -> bool:
        """True when admission will try a warm-pool claim for this pod.
        Claim-eligible pods are created GATED even when they are not gang
        pods (serving predictor replicas): an ungated manifest would let
        the node agent cold-spawn the twin in the window between create
        and the claim that deletes it — two processes racing one bind."""
        return self.warm_pool is not None and self.warm_pool.eligible(pod)

    def create_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        manifest = pod_to_manifest(pod, self.image)
        if not pod.gang and self._claim_eligible(pod):
            manifest["spec"]["schedulingGates"] = [{"name": GANG_GATE}]
        try:
            doc = self._request("POST", self._pod_path(pod.namespace),
                                manifest)
        except KubeApiError as e:
            if e.code == 409:
                # lost a create race. In cache-serving mode the winner may
                # not have inserted its cache entry yet (its POST returned
                # but the lock section hasn't run) — fold the server copy
                # so this thread's very next read already sees the pod
                if self._cache_covers(pod.namespace):
                    try:
                        self._fold(self._request(
                            "GET", self._pod_path(pod.namespace, pod.name)))
                    except (KubeApiError, OSError,
                            http.client.HTTPException):
                        pass    # best-effort only: the winner's insert or
                        #         the next watch event repairs the cache
                raise KeyError(f"pod {key} exists") from e
            raise
        with self._lock:
            try:
                pod._rv = int(  # noqa: SLF001 — incarnation fencing
                    (doc.get("metadata") or {}).get("resourceVersion", 0))
            except (TypeError, ValueError):
                pod._rv = 0
            existing = self._pods.get(key)
            if (existing is not None and existing is not pod and pod._rv
                    and getattr(existing, "_rv", 0) >= pod._rv):
                # rv >= this POST's creation rv: the informer folded THIS
                # incarnation's watch events before this section ran. That
                # object carries remote state at least as new as the POST
                # (phase/node, possibly already terminal) and concurrent
                # readers hold it — merge the creator's env into it instead
                # of clobbering the entry.
                for k, v in pod.env.items():
                    existing.env.setdefault(k, v)
            else:
                # no entry, or one whose rv predates this POST — a stale
                # prior incarnation of the name (the server must have
                # deleted it for our POST to succeed). Replace it: merging
                # could wedge the new pod terminal forever (_apply_remote
                # never resurrects), and the fresh rv fences out the old
                # incarnation's lagging watch events.
                self._pods[key] = pod
            if pod.gang or self._claim_eligible(pod):
                self._gated.add(key)
            self._pushed_env[key] = dict(pod.env)

    def start_pod(self, pod: Pod) -> None:
        """Gang admission: lift the scheduling gate so the scheduler may
        place the pod, and publish late-bound env as annotations.

        With a warm pool attached, admission first tries to CLAIM a
        pre-warmed standby pod (label-patched into the gang, worker argv
        delivered to its resident zygote) instead of letting the scheduler
        place the cold one — the claim happens here, not at create time,
        so a gang-queued job never hogs standby capacity while it waits.
        On a successful claim the cold gated twin (never schedulable —
        its gate was still set) is deleted and the job pod name aliases
        to the standby pod. A dry or dead pool falls back to the normal
        cold path below, counted by the pool."""
        key = (pod.namespace, pod.name)
        pool = self.warm_pool
        if pool is not None:
            with self._lock:
                already = key in self._claims
            if not already and pool.eligible(pod):
                claimed = pool.claim_and_exec(pod)
                if claimed is not None:
                    with self._lock:
                        self._claims[key] = (claimed.namespace,
                                             claimed.name)
                    # the cold twin _ensure_pods created is dead weight:
                    # still gated, never scheduled — remove it so the gang
                    # is exactly the claimed pods + any cold fallbacks
                    self.delete_pod(pod.namespace, pod.name)
                    return
        patch: dict = {}
        with self._lock:
            if key in self._gated:
                patch["spec"] = {"schedulingGates": []}
                self._gated.discard(key)
            extra = {k: v for k, v in pod.env.items()
                     if self._pushed_env.get(key, {}).get(k) != v}
            if extra:
                patch.setdefault("metadata", {})["annotations"] = {
                    ENV_ANNOTATION_PREFIX + k: str(v)
                    for k, v in extra.items()}
                self._pushed_env.setdefault(key, {}).update(extra)
        if patch:
            self._request(
                "PATCH", self._pod_path(pod.namespace, pod.name), patch,
                content_type="application/merge-patch+json")

    def delete_pod(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        try:
            self._request(
                "DELETE",
                self._pod_path(namespace, name) + "?gracePeriodSeconds=0")
        except KubeApiError as e:
            if e.code != 404:
                raise
        with self._lock:
            self._pods.pop(key, None)
            self._gated.discard(key)
            self._pushed_env.pop(key, None)
            # a deleted standby/claimed pod takes its job-name aliases
            # with it (aliases point AT the warm pod, keyed by job name)
            for alias, target in list(self._claims.items()):
                if target == key:
                    self._claims.pop(alias, None)

    def release_claim(self, namespace: str, name: str) -> None:
        """Drop every job-pod-name alias pointing at ``(namespace,
        name)`` WITHOUT deleting the pod — the warm-pool reclaim arc: a
        returned standby keeps existing under its own name, but the
        stopped trial's pod name must stop resolving to it (a late
        ``get_pod``/``delete_pod`` through the alias would hit the next
        claimant's pod)."""
        key = (namespace, name)
        with self._lock:
            for alias, target in list(self._claims.items()):
                if target == key:
                    self._claims.pop(alias, None)

    def patch_pod(self, namespace: str, name: str, patch: dict,
                  expect_rv: Optional[int] = None) -> dict:
        """Generic JSON merge patch on a pod. ``expect_rv`` makes it a
        compare-and-swap: the patch names that resourceVersion and the
        apiserver 409s if the object moved — the primitive the warm-pool
        claim race rests on (exactly one claimant wins)."""
        body = json.loads(json.dumps(patch))
        if expect_rv is not None:
            body.setdefault("metadata", {})["resourceVersion"] = str(
                expect_rv)
        doc = self._request(
            "PATCH", self._pod_path(namespace, name), body,
            content_type="application/merge-patch+json")
        if doc:
            self._fold(doc)
        return doc

    # --------------------------------------------- elastic recovery --

    def can_restart_in_place(self, pod: Pod) -> bool:
        """Whether the survivor re-rendezvous signal can reach this pod.
        Claimed warm-pool standbys run their worker as a zygote FORK the
        node agent cannot bounce (the claim connection owns its lifetime)
        — restarting one means killing the zygote, i.e. losing the pod;
        that forces the counted gang-restart fallback instead."""
        with self._lock:
            return (pod.namespace, pod.name) not in set(
                self._claims.values())

    def restart_pod_process(self, namespace: str, name: str,
                            env_updates: Optional[dict] = None) -> bool:
        """Signal an in-place process restart (elastic recovery): bump the
        restart-epoch annotation (+ publish the new env as annotations);
        the node agent kills and respawns the pod's process with the
        merged env. The pod object — claim, labels, scheduling — is
        untouched."""
        key = (namespace, name)
        with self._lock:
            target = self._claims.get(key)
        if target is not None:
            namespace, name = target
        ann = {RESTART_EPOCH_ANNOTATION:
               (env_updates or {}).get("KFT_RENDEZVOUS_EPOCH")
               or str(time.time())}
        for k, v in (env_updates or {}).items():
            ann[ENV_ANNOTATION_PREFIX + k] = str(v)
        try:
            self.patch_pod(namespace, name,
                           {"metadata": {"annotations": ann}})
        except (KubeApiError, OSError):
            return False
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is not None:
                pod.env.update(env_updates or {})
                # new process incarnation: the heartbeat grace clock (and
                # the incarnation-aware staleness check) key on
                # created_at — the bounced survivor must get startup
                # grace, not the old incarnation's stale-beat timeout
                pod.created_at = time.time()
        return True

    def _apply_remote(self, pod: Pod, doc: dict) -> None:
        try:
            rv = int((doc.get("metadata") or {})
                     .get("resourceVersion", 0) or 0)
            if rv and rv < getattr(pod, "_rv", 0):
                # incarnation fence (the non-DELETED half; watch_pods
                # fences DELETED): a lagging event carrying an OLDER rv —
                # a prior same-name incarnation's MODIFIED, or a replay
                # after watch restart — must not rewrite state the cache
                # learned from a newer rv (e.g. wedge a freshly
                # re-created pod terminal)
                return
            pod._rv = max(getattr(pod, "_rv", 0), rv)
        except (TypeError, ValueError):
            pass
        phase, exit_code = _manifest_status(doc)
        ann = (doc.get("metadata") or {}).get("annotations")
        if ann is not None:
            # annotations are server truth that changes at runtime (zygote
            # address, restart-epoch, late-bound env) — mirror them so the
            # kubelet/consumers see updates, not the creation snapshot
            pod.annotations = dict(ann)
        labels = (doc.get("metadata") or {}).get("labels")
        if labels is not None:
            # labels are server truth and DO change at runtime here: a
            # warm-pool claim label-patches a standby pod into the gang —
            # every client's cache must see the pod switch selectors
            pod.labels = dict(labels)
        gates = (doc.get("spec", {}) or {}).get("schedulingGates") or []
        if not gates:
            # another controller replica (or this one, earlier) lifted it.
            # One-way latch on `scheduled` (never un-admit from a lagging
            # event): the kubelet role watches this bit to know the pod
            # may run
            pod.scheduled = True
            self._gated.discard((pod.namespace, pod.name))
        else:
            # still gated server-side — crucial for pods ADOPTED after a
            # controller restart: start_pod must know to lift the gate
            self._gated.add((pod.namespace, pod.name))
        if pod.phase in _TERMINAL:
            return                      # terminal-wins: never resurrect
        pod.phase = phase
        if exit_code is not None:
            pod.exit_code = exit_code
        node = (doc.get("spec", {}) or {}).get("nodeName")
        if node:
            pod.node = node

    def _cache_covers(self, namespace: str) -> bool:
        return self._cache_serving and (
            not self._cache_namespace or self._cache_namespace == namespace)

    def _fold(self, doc: dict) -> Pod:
        """Merge a server manifest into the informer cache (caller need
        not hold the lock)."""
        key = (doc["metadata"].get("namespace") or "default",
               doc["metadata"]["name"])
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                pod = self._pod_from_manifest(doc)
                self._pods[key] = pod
            self._apply_remote(pod, doc)
            return pod

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        key = (namespace, name)
        with self._lock:
            target = self._claims.get(key)
        if target is not None:
            # warm-claim alias: the job pod's identity is served by a
            # claimed standby pod under its own (un-renameable) name
            got = self.get_pod(*target)
            if got is None:
                with self._lock:       # claimed pod gone: alias is stale
                    if self._claims.get(key) == target:
                        self._claims.pop(key, None)
            return got
        if self._cache_covers(namespace):
            with self._lock:
                return self._pods.get(key)
        try:
            doc = self._request("GET", self._pod_path(namespace, name))
        except KubeApiError as e:
            if e.code == 404:
                with self._lock:
                    self._pods.pop(key, None)
                return None
            raise
        return self._fold(doc)

    def list_pods(self, namespace: str,
                  selector: dict[str, str]) -> list[Pod]:
        if self._cache_covers(namespace):
            # ns "" = cluster-wide, mirroring the REST path (/api/v1/pods):
            # a cluster-scope informer must serve cluster-scope lists from
            # its cache, not an always-empty namespace match
            with self._lock:
                return [p for (ns, _), p in self._pods.items()
                        if (not namespace or ns == namespace)
                        and all(p.labels.get(k) == v
                                for k, v in selector.items())]
        return self._list_pods_rest(namespace, selector)

    def _list_pods_rest(self, namespace: str,
                        selector: dict[str, str]) -> list[Pod]:
        t0 = time.time()
        sel = ",".join(f"{k}={v}" for k, v in sorted(selector.items()))
        path = self._pod_path(namespace)
        if sel:
            path += f"?labelSelector={quote(sel)}"
        body = self._request("GET", path)
        try:
            # seed the watch cursor from the list (the list+watch resume
            # semantics): a watch opened after this LIST must start at its
            # resourceVersion, not replay the server's whole history
            self._watch_rv = max(
                getattr(self, "_watch_rv", 0),
                int((body.get("metadata") or {})
                    .get("resourceVersion", 0) or 0))
        except (TypeError, ValueError):
            pass
        docs = body.get("items", [])
        out = [self._fold(doc) for doc in docs]
        with self._lock:
            remote = {(p.namespace, p.name) for p in out}
            # reap cache entries whose pods vanished server-side; skip pods
            # created after the LIST left (a POST racing the resync must
            # not evict its own fresh cache entry)
            for key in [k for k, p in self._pods.items()
                        if (not namespace or k[0] == namespace)
                        and k not in remote and p.created_at < t0
                        and all(p.labels.get(lk) == lv
                                for lk, lv in selector.items())]:
                self._pods.pop(key, None)
                self._gated.discard(key)
                self._pushed_env.pop(key, None)
        return out

    def _pod_from_manifest(self, doc: dict) -> Pod:
        meta = doc.get("metadata", {})
        spec = doc.get("spec", {}) or {}
        containers = spec.get("containers") or [{}]
        env = {e["name"]: e.get("value", "")
               for e in containers[0].get("env", []) or []}
        for k, v in (meta.get("annotations") or {}).items():
            if k.startswith(ENV_ANNOTATION_PREFIX):
                env.setdefault(k[len(ENV_ANNOTATION_PREFIX):], v)
        pod = Pod(
            name=meta["name"], namespace=meta.get("namespace") or "default",
            labels=dict(meta.get("labels") or {}),
            env=env,
            command=list(containers[0].get("command") or []),
            init_command=list(
                (spec.get("initContainers") or [{}])[0].get("command")
                or []),
            annotations=dict(meta.get("annotations") or {}),
        )
        pod.scheduled = not spec.get("schedulingGates")
        pod.gang = bool(spec.get("schedulingGates"))
        # adoption bookkeeping: what the server already has needs no push
        self._pushed_env[(pod.namespace, pod.name)] = dict(env)
        # warm-claim adoption: a restarted controller rebuilds the job-pod
        # name alias from the claim annotation alone
        claimed_as = (meta.get("annotations") or {}).get(
            CLAIMED_AS_ANNOTATION)
        if claimed_as:
            self._claims[(pod.namespace, claimed_as)] = (
                pod.namespace, pod.name)
        return pod

    # -------------------------------------------------- service verbs --

    def create_service(self, svc: Service) -> None:
        manifest = {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": svc.name, "namespace": svc.namespace},
            "spec": {
                "clusterIP": "None",       # headless: per-pod DNS
                "selector": dict(svc.selector),
                "ports": [{"port": svc.port}],
            },
        }
        try:
            self._request(
                "POST",
                f"/api/v1/namespaces/{quote(svc.namespace)}/services",
                manifest)
        except KubeApiError as e:
            if e.code != 409:
                raise
        with self._lock:
            self._services[(svc.namespace, svc.name)] = svc

    def delete_service(self, namespace: str, name: str) -> None:
        try:
            self._request(
                "DELETE",
                f"/api/v1/namespaces/{quote(namespace)}/services/"
                f"{quote(name)}")
        except KubeApiError as e:
            if e.code != 404:
                raise
        with self._lock:
            self._services.pop((namespace, name), None)

    def get_service(self, namespace: str, name: str) -> Optional[Service]:
        with self._lock:
            svc = self._services.get((namespace, name))
        if svc is not None:
            return svc
        try:
            doc = self._request(
                "GET",
                f"/api/v1/namespaces/{quote(namespace)}/services/"
                f"{quote(name)}")
        except KubeApiError as e:
            if e.code == 404:
                return None
            raise
        spec = doc.get("spec", {}) or {}
        svc = Service(
            name=name, namespace=namespace,
            selector=dict(spec.get("selector") or {}),
            port=int((spec.get("ports") or [{"port": 0}])[0]["port"]))
        with self._lock:
            self._services[(namespace, name)] = svc
        return svc

    def resolve(self, namespace: str, service: str) -> str:
        """Cluster-DNS convention — resolvable from any pod in-cluster."""
        svc = self.get_service(namespace, service)
        port = svc.port if svc else 0
        return f"{service}.{namespace}.svc:{port}"

    # ------------------------------------------------------- watching --

    def watch_pods(self, namespace: str, selector: dict[str, str] = {},
                   timeout_s: float = 30.0,
                   from_rv: int = 0) -> Iterator[tuple[str, Pod]]:
        """Stream (event_type, Pod) from the apiserver watch endpoint —
        the informer feed. Yields until the server closes the window.
        ``from_rv=0`` replays retained history, so a watch opened after an
        event still observes it (the list+watch resume semantics)."""
        sel = ",".join(f"{k}={v}" for k, v in sorted(selector.items()))
        path = (self._pod_path(namespace)
                + f"?watch=true&timeoutSeconds={int(timeout_s)}"
                + f"&resourceVersion={int(from_rv)}")
        if sel:
            path += f"&labelSelector={quote(sel)}"
        if self.scheme == "https":
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=timeout_s + 10,
                context=self._ssl)
        else:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout_s + 10)
        try:
            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    event = json.loads(line)
                    doc = event["object"]
                    try:
                        self._watch_rv = max(
                            getattr(self, "_watch_rv", 0),
                            int(doc["metadata"].get("resourceVersion", 0)))
                    except (TypeError, ValueError):
                        pass
                    key = (doc["metadata"].get("namespace") or "default",
                           doc["metadata"]["name"])
                    with self._lock:
                        pod = self._pods.get(key)
                        if event["type"] == "DELETED":
                            # incarnation fence: a lagging DELETED for an
                            # old same-name pod must not evict a freshly
                            # re-created one (its rv is newer than the
                            # deletion event's)
                            try:
                                ev_rv = int(doc["metadata"].get(
                                    "resourceVersion", 0) or 0)
                            except (TypeError, ValueError):
                                ev_rv = 0
                            if pod is None or \
                                    getattr(pod, "_rv", 0) <= ev_rv:
                                self._pods.pop(key, None)
                            if pod is None:
                                pod = self._pod_from_manifest(doc)
                        else:
                            if pod is None:
                                pod = self._pod_from_manifest(doc)
                                self._pods[key] = pod
                            self._apply_remote(pod, doc)
                    yield event["type"], pod
        finally:
            conn.close()

    def add_pod_event_listener(self, cb) -> None:
        """Subscribe to folded watch events (cb(event_type, pod)). Unlike
        the legacy single-slot ``on_pod_event``, any number of subscribers
        coexist — a second Operator sharing this cluster cannot silently
        detach the first (ADVICE r5 #1)."""
        with self._lock:
            self._pod_event_subs.append(cb)

    def remove_pod_event_listener(self, cb) -> None:
        with self._lock:
            try:
                self._pod_event_subs.remove(cb)
            except ValueError:
                pass

    def _dispatch_pod_event(self, etype: str, pod: Pod) -> None:
        cbs = [self.on_pod_event] if self.on_pod_event is not None else []
        with self._lock:
            cbs += list(self._pod_event_subs)
        for cb in cbs:
            try:
                cb(etype, pod)
            except Exception:
                pass

    def start_informer(self, namespace: str = "",
                       selector: dict[str, str] = {},
                       resync_period_s: float = 30.0) -> bool:
        """List+watch informer (the client-go reflector role): one priming
        LIST, then a background watch keeps the cache fresh. With an empty
        selector, get_pod/list_pods switch to cache-serving — steady-state
        reconciles issue ZERO apiserver reads between pod events; a resync
        LIST every ``resync_period_s`` repairs any drift. ``on_pod_event``
        / ``add_pod_event_listener`` subscribers fire after each folded
        event so the daemon can reconcile on events instead of polling.

        Returns True iff THIS call started the informer thread — the
        ownership token: only the caller that got True may stop_informer()
        (a second Operator sharing the cluster gets False and must leave
        the running informer alone, ADVICE r5 #1)."""
        if self._informer is not None:
            return False
        self._cache_namespace = namespace
        try:
            self._list_pods_rest(namespace, dict(selector))     # prime
            if not selector:
                self._cache_serving = True
        except Exception:
            # apiserver transiently down at boot: don't crash startup —
            # reads stay REST-backed until the loop's first successful
            # resync primes the cache and flips cache-serving on
            pass

        def loop():
            try:
                if not self._cache_serving:
                    while not self._informer_stop.is_set():
                        try:
                            self._list_pods_rest(namespace, dict(selector))
                            if not selector:
                                self._cache_serving = True
                            break
                        except Exception:
                            if self._informer_stop.wait(1.0):
                                return
                last_resync = time.monotonic()
                while not self._informer_stop.is_set():
                    try:
                        for etype, pod in self.watch_pods(
                                namespace, selector, timeout_s=10,
                                from_rv=getattr(self, "_watch_rv", 0)):
                            if self._informer_stop.is_set():
                                return
                            self._dispatch_pod_event(etype, pod)
                    except Exception:
                        if self._informer_stop.wait(1.0):
                            return
                    if time.monotonic() - last_resync >= resync_period_s:
                        last_resync = time.monotonic()
                        try:
                            self._list_pods_rest(namespace, dict(selector))
                        except Exception:
                            pass
            finally:
                # self-deregister: if stop_informer timed out waiting on a
                # blocked watch read, this (eventual) exit is what frees
                # the slot for a future start_informer
                with self._lock:
                    if self._informer is threading.current_thread():
                        self._informer = None
                        self._informer_stop.clear()

        self._informer = threading.Thread(
            target=loop, daemon=True, name="kube-informer")
        self._informer.start()
        return True

    @property
    def informer_running(self) -> bool:
        return self._informer is not None

    def stop_informer(self) -> None:
        self._informer_stop.set()
        self._cache_serving = False
        t = self._informer
        if t is not None:
            t.join(timeout=15)
            # if still blocked in a watch read (socket timeout can be
            # ~20s), leave the stop flag SET — the loop's finally block
            # deregisters and clears it when the read finally returns;
            # clearing here would un-stop the thread
            return
        self._informer_stop.clear()

    # ------------------------------------------------ generic install --

    def apply(self, doc: dict) -> dict:
        """kubectl-apply role: POST, falling back to PUT on conflict.
        Routes by apiVersion/kind (platform/manifests.py output)."""
        api, kind = doc.get("apiVersion", "v1"), doc.get("kind", "")
        plural = _KIND_PATHS.get((api, kind))
        if plural is None:
            plural = kind.lower() + "s"       # CRD convention
        prefix = "/api/v1" if api == "v1" else f"/apis/{api}"
        name = doc.get("metadata", {}).get("name", "")
        if kind in _CLUSTER_SCOPED:
            base = f"{prefix}/{plural}"
        else:
            ns = doc.get("metadata", {}).get("namespace") or "default"
            base = f"{prefix}/namespaces/{quote(ns)}/{plural}"
        try:
            return self._request("POST", base, doc)
        except KubeApiError as e:
            if e.code != 409:
                raise
            return self._request("PUT", f"{base}/{quote(name)}", doc)

    # ------------------------------------------------------- CR verbs --

    def save_cr(self, group: str, version: str, plural: str,
                namespace: str, name: str, doc: dict) -> None:
        base = f"/apis/{group}/{version}/namespaces/{quote(namespace)}/" \
               f"{plural}"
        try:
            self._request("POST", base, doc)
        except KubeApiError as e:
            if e.code != 409:
                raise
            self._request("PUT", f"{base}/{quote(name)}", doc)

    def delete_cr(self, group: str, version: str, plural: str,
                  namespace: str, name: str) -> None:
        try:
            self._request(
                "DELETE",
                f"/apis/{group}/{version}/namespaces/{quote(namespace)}/"
                f"{plural}/{quote(name)}")
        except KubeApiError as e:
            if e.code != 404:
                raise

    def list_cr(self, group: str, version: str, plural: str) -> list[dict]:
        return self._request(
            "GET", f"/apis/{group}/{version}/{plural}").get("items", [])

    # ------------------------------------------- envtest-style helpers --

    def set_phase(self, namespace: str, name: str, phase: PodPhase,
                  exit_code: Optional[int] = None) -> None:
        """Drive a pod's phase THROUGH the apiserver (the test suite's
        kubelet role — same surface FakeCluster exposes in-memory)."""
        status: dict = {"phase": phase.value}
        if exit_code is not None:
            status["containerStatuses"] = [{
                "name": "worker",
                "state": {"terminated": {"exitCode": int(exit_code)}}}]
        doc = self._request(
            "PATCH", self._pod_path(namespace, name, "status"),
            {"status": status},
            content_type="application/merge-patch+json")
        # fold into the cache now (direct, not via get_pod: with the
        # informer cache serving reads, get_pod would not refetch)
        if doc:
            self._fold(doc)

    def run_scheduled(self) -> None:
        """Pretend kubelet: every gate-lifted Pending pod goes Running."""
        with self._lock:
            keys = [k for k, p in self._pods.items()
                    if p.phase == PodPhase.PENDING and p.scheduled
                    and k not in self._gated]
        for ns, name in keys:
            self.set_phase(ns, name, PodPhase.RUNNING)


_JOB_PLURALS = {
    "JAXJob": "jaxjobs", "TFJob": "tfjobs",
    "PyTorchJob": "pytorchjobs", "XGBoostJob": "xgboostjobs",
}
JOB_CR_GROUP = "kubeflow-tpu.org"
JOB_CR_VERSION = "v1"


class JobCRStore:
    """Jobs as custom resources IN the apiserver — the reference's etcd
    role. The controller is stateless for job specs: submit persists the
    CR (spec + uid + terminal condition), delete removes it, and a
    restarted controller `load_all()`s and adopts its existing pods (the
    uid round-trips, so the job-uid pod selector still matches).
    Wire via ``JobController.job_store``."""

    def __init__(self, cluster: KubeCluster):
        self.cluster = cluster

    @staticmethod
    def _plural(kind: str) -> str:
        return _JOB_PLURALS.get(kind, kind.lower() + "s")

    def save(self, job) -> None:
        from kubeflow_tpu.api.types import to_yaml
        import yaml as _yaml

        doc = _yaml.safe_load(to_yaml(job))
        self.cluster.save_cr(
            JOB_CR_GROUP, JOB_CR_VERSION, self._plural(job.kind),
            job.namespace, job.name, doc)

    def delete(self, job) -> None:
        self.cluster.delete_cr(
            JOB_CR_GROUP, JOB_CR_VERSION, self._plural(job.kind),
            job.namespace, job.name)

    def load_all(self) -> list:
        from kubeflow_tpu.api.types import from_yaml
        import yaml as _yaml

        out = []
        for plural in _JOB_PLURALS.values():
            try:
                docs = self.cluster.list_cr(
                    JOB_CR_GROUP, JOB_CR_VERSION, plural)
            except KubeApiError:
                continue
            for doc in docs:
                out.append(from_yaml(_yaml.safe_dump(doc)))
        return out
