from kubeflow_tpu.controller.chaos import FaultInjector
from kubeflow_tpu.controller.cluster import (
    Cluster, FakeCluster, LocalProcessCluster, Pod, PodPhase, Service,
)
from kubeflow_tpu.controller.gang import GangScheduler, PodGroup, SlicePool
from kubeflow_tpu.controller.operator import Metrics, Operator
from kubeflow_tpu.controller.fake_apiserver import FakeKubeApiServer
from kubeflow_tpu.controller.kube import KubeCluster
from kubeflow_tpu.controller.kubelet import FakeKubelet
from kubeflow_tpu.controller.reconciler import JobController, pod_name
from kubeflow_tpu.controller.warmpool import WarmPoolController
