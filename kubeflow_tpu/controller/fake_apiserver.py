"""A minimal in-process Kubernetes apiserver — the envtest role.

The reference tests its controllers against envtest: a real apiserver+etcd
with no kubelets, where "pods are created but never run" and tests drive
phases by patching status (SURVEY.md §4.2). This module is that harness for
the `KubeCluster` backend: an HTTP server speaking the minimal apiserver
subset the controllers use —

- typed + generic object storage for core (``/api/v1``) and group
  (``/apis/{group}/{version}``) resources, namespaced or cluster-scoped;
- POST (409 on exists), GET, PUT, JSON-merge PATCH, DELETE;
- optimistic concurrency on PATCH: a patch carrying
  ``metadata.resourceVersion`` is a compare-and-swap — mismatch returns
  409 Conflict, exactly the real apiserver's update-conflict semantics
  (this is what makes a warm-pod claim race have exactly one winner);
- list with ``labelSelector=k=v,k2=v2``;
- the ``/status`` subresource (how tests play the kubelet);
- ``?watch=true`` chunked streaming of ADDED/MODIFIED/DELETED events with
  ``resourceVersion`` resume (how the informer cache stays fresh).

It is intentionally NOT a validation-complete apiserver: schema checking,
admission chains, and RBAC live in this repo's own webhook/auth layers
(SURVEY.md §2.1, §2.6); what matters here is wire-level parity for the
client in `controller/kube.py`, so the same client drives a real apiserver
unchanged.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


def _merge(dst: dict, patch: dict) -> dict:
    """RFC 7386 JSON merge patch."""
    for k, v in patch.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = v
    return dst


def _match_selector(obj: dict, selector: str) -> bool:
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    for clause in selector.split(","):
        if not clause:
            continue
        if "!=" in clause:
            k, v = clause.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "=" in clause:
            k, v = clause.replace("==", "=").split("=", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        else:                       # bare key: existence
            if clause.strip() not in labels:
                return False
    return True


class _Store:
    """Versioned object store + event log for watches."""

    def __init__(self):
        self.lock = threading.Condition()
        self.rv = 0
        # (resource path prefix, namespace or "", name) -> object dict
        self.objects: dict[tuple[str, str, str], dict] = {}
        # append-only: (rv, type, resource, namespace, object snapshot)
        self.events: list[tuple[int, str, str, str, dict]] = []

    def bump(self) -> int:
        self.rv += 1
        return self.rv

    def record(self, etype: str, resource: str, ns: str, obj: dict):
        self.events.append(
            (self.rv, etype, resource, ns, json.loads(json.dumps(obj))))
        if len(self.events) > 10000:        # bounded history
            del self.events[:5000]
        self.lock.notify_all()


class FakeKubeApiServer:
    """`start()` binds an ephemeral port; `url` is the apiserver base."""

    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self.store = _Store()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        # wire-level accounting for informer tests: how many LIST (GET
        # collection), GET (single object), and WATCH requests arrived.
        # The informer architecture's whole point is that steady-state
        # reads hit the cache, not the server — these counters prove it.
        self.requests: dict[str, int] = {"LIST": 0, "GET": 0, "WATCH": 0}

    # ------------------------------------------------------------ http --

    def start(self) -> "FakeKubeApiServer":
        store = self.store
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):      # quiet
                pass

            # -- plumbing --------------------------------------------

            def _send_json(self, code: int, obj: dict):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _err(self, code: int, reason: str, message: str):
                self._send_json(code, {
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Failure", "reason": reason,
                    "message": message, "code": code})

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                return json.loads(raw) if raw else {}

            def _route(self):
                """Parse an apiserver path into
                (resource_prefix, namespace, name, subresource)."""
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                # /api/v1/... or /apis/{group}/{version}/...
                if parts[:2] == ["api", "v1"]:
                    rest, prefix = parts[2:], "api/v1"
                elif parts[:1] == ["apis"] and len(parts) >= 3:
                    rest, prefix = parts[3:], "/".join(parts[:3])
                else:
                    return None
                ns = ""
                if rest[:1] == ["namespaces"] and len(rest) >= 3:
                    ns, rest = rest[1], rest[2:]
                elif rest[:1] == ["namespaces"] and len(rest) == 2:
                    # namespace object itself: /api/v1/namespaces/{name}
                    return (f"{prefix}/namespaces", "", rest[1], "", q)
                if not rest:
                    return None
                resource = f"{prefix}/{rest[0]}"
                name = rest[1] if len(rest) > 1 else ""
                sub = rest[2] if len(rest) > 2 else ""
                return (resource, ns, name, sub, q)

            # -- verbs -----------------------------------------------

            def do_GET(self):
                r = self._route()
                if r is None:
                    return self._err(404, "NotFound", self.path)
                resource, ns, name, _sub, q = r
                kind = ("WATCH" if q.get("watch") == "true"
                        else "GET" if name else "LIST")
                with store.lock:
                    srv.requests[kind] = srv.requests.get(kind, 0) + 1
                    if name:
                        obj = store.objects.get((resource, ns, name))
                        if obj is None:
                            return self._err(404, "NotFound",
                                             f"{resource} {ns}/{name}")
                        return self._send_json(200, obj)
                    items = [o for (res, ons, _), o in
                             sorted(store.objects.items())
                             if res == resource and (not ns or ons == ns)
                             and _match_selector(
                                 o, q.get("labelSelector", ""))]
                    rv = store.rv
                if q.get("watch") == "true":
                    return self._watch(resource, ns,
                                       q.get("labelSelector", ""),
                                       int(q.get("resourceVersion", rv)),
                                       float(q.get("timeoutSeconds", 30)))
                self._send_json(200, {
                    "kind": "List", "apiVersion": "v1",
                    "metadata": {"resourceVersion": str(rv)},
                    "items": items})

            def _watch(self, resource, ns, selector, from_rv, timeout):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def emit(etype, obj):
                    line = json.dumps(
                        {"type": etype, "object": obj}).encode() + b"\n"
                    self.wfile.write(
                        f"{len(line):x}\r\n".encode() + line + b"\r\n")
                    self.wfile.flush()

                import time as _t
                end = _t.monotonic() + timeout
                last = from_rv
                try:
                    while _t.monotonic() < end:
                        with store.lock:
                            pending = [
                                e for e in store.events
                                if e[0] > last and e[2] == resource
                                and (not ns or e[3] == ns)
                                and _match_selector(e[4], selector)]
                            if not pending:
                                store.lock.wait(
                                    min(1.0, end - _t.monotonic()))
                                pending = [
                                    e for e in store.events
                                    if e[0] > last and e[2] == resource
                                    and (not ns or e[3] == ns)
                                    and _match_selector(e[4], selector)]
                            if pending:
                                last = max(e[0] for e in pending)
                        for _, etype, _, _, obj in pending:
                            emit(etype, obj)
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass
                self.close_connection = True

            def do_POST(self):
                r = self._route()
                if r is None:
                    return self._err(404, "NotFound", self.path)
                resource, ns, _name, _sub, _q = r
                obj = self._body()
                name = obj.get("metadata", {}).get("name", "")
                if not name:
                    return self._err(422, "Invalid", "metadata.name required")
                key = (resource, ns, name)
                with store.lock:
                    if key in store.objects:
                        return self._err(
                            409, "AlreadyExists", f"{resource} {name}")
                    obj.setdefault("metadata", {})
                    obj["metadata"]["namespace"] = ns or None
                    obj["metadata"]["resourceVersion"] = str(store.bump())
                    obj.setdefault("status", {})
                    if resource.endswith("/pods"):
                        obj["status"].setdefault("phase", "Pending")
                    store.objects[key] = obj
                    store.record("ADDED", resource, ns, obj)
                self._send_json(201, obj)

            def do_PUT(self):
                r = self._route()
                if r is None or not r[2]:
                    return self._err(404, "NotFound", self.path)
                resource, ns, name, _sub, _q = r
                obj = self._body()
                key = (resource, ns, name)
                with store.lock:
                    if key not in store.objects:
                        return self._err(404, "NotFound", name)
                    obj.setdefault("metadata", {})
                    obj["metadata"]["namespace"] = ns or None
                    obj["metadata"]["resourceVersion"] = str(store.bump())
                    store.objects[key] = obj
                    store.record("MODIFIED", resource, ns, obj)
                self._send_json(200, obj)

            def do_PATCH(self):
                r = self._route()
                if r is None or not r[2]:
                    return self._err(404, "NotFound", self.path)
                resource, ns, name, sub, _q = r
                patch = self._body()
                key = (resource, ns, name)
                with store.lock:
                    obj = store.objects.get(key)
                    if obj is None:
                        return self._err(404, "NotFound", name)
                    # compare-and-swap: a patch that names a resourceVersion
                    # only applies against that exact version (the claim
                    # fence). Pop it either way — the server owns rv.
                    want_rv = (patch.get("metadata") or {}).pop(
                        "resourceVersion", None)
                    if want_rv is not None and str(want_rv) != str(
                            obj.get("metadata", {}).get(
                                "resourceVersion", "")):
                        return self._err(
                            409, "Conflict",
                            f"resourceVersion {want_rv} is stale")
                    if sub == "status":
                        _merge(obj.setdefault("status", {}),
                               patch.get("status", patch))
                    else:
                        _merge(obj, patch)
                    obj["metadata"]["resourceVersion"] = str(store.bump())
                    store.record("MODIFIED", resource, ns, obj)
                self._send_json(200, obj)

            def do_DELETE(self):
                r = self._route()
                if r is None or not r[2]:
                    return self._err(404, "NotFound", self.path)
                resource, ns, name, _sub, _q = r
                key = (resource, ns, name)
                with store.lock:
                    obj = store.objects.pop(key, None)
                    if obj is None:
                        return self._err(404, "NotFound", name)
                    store.bump()
                    store.record("DELETED", resource, ns, obj)
                self._send_json(200, {"kind": "Status", "status": "Success"})

        self._httpd = ThreadingHTTPServer((self.host, 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fake-apiserver")
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # ------------------------------------------------------ inspection --

    def get(self, resource: str, namespace: str, name: str) -> Optional[dict]:
        with self.store.lock:
            obj = self.store.objects.get((resource, namespace, name))
            return json.loads(json.dumps(obj)) if obj else None

    def count(self, resource: str) -> int:
        with self.store.lock:
            return sum(1 for (res, _, _) in self.store.objects
                       if res == resource)
