"""Cluster abstraction the job controller reconciles against.

The reference controller talks to the Kubernetes apiserver via client-go
informers (SURVEY.md §3.1). Here the same reconcile logic runs over a small
`Cluster` interface with three implementations:

- `FakeCluster` — in-memory pods/services whose phases tests drive by hand;
  the envtest equivalent (SURVEY.md §4.2: 'pods are created but never run').
- `LocalProcessCluster` — pods are real OS processes on this machine;
  headless services resolve to 127.0.0.1 ports. This gives REAL
  jax.distributed multi-process rendezvous in CI without a cluster.
- `ManifestCluster` — renders Kubernetes YAML (Pod/Service/PodGroup with GKE
  TPU node selectors) for a real deployment; no cluster needed to test the
  rendering.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Optional, Protocol


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class Pod:
    name: str
    namespace: str
    labels: dict[str, str]
    env: dict[str, str]
    command: list[str]
    # initContainer role: runs to completion before `command` starts (the
    # reference's storage-initializer injection, SURVEY.md §2.4)
    init_command: list[str] = dataclasses.field(default_factory=list)
    phase: PodPhase = PodPhase.PENDING
    exit_code: Optional[int] = None
    node: Optional[str] = None
    scheduled: bool = False            # gang admission happened
    # gang-scheduled pods carry a scheduling gate on real backends until the
    # whole slice group is admitted (the job reconciler's whole-slice atom);
    # Deployment-style pods (serving/notebook/tensorboard) never gate — they
    # schedule individually the moment they are admitted
    gang: bool = False
    created_at: float = dataclasses.field(default_factory=time.time)
    # real-cluster placement (rendered by the KubeCluster backend; ignored
    # by in-memory/local-process backends): container image, GKE TPU
    # topology nodeSelector, and resource limits (google.com/tpu etc.)
    image: str = ""
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    resources: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Service:
    name: str
    namespace: str
    selector: dict[str, str]
    port: int


class Cluster(Protocol):
    def create_pod(self, pod: Pod) -> None: ...
    def delete_pod(self, namespace: str, name: str) -> None: ...
    def get_pod(self, namespace: str, name: str) -> Optional[Pod]: ...
    def list_pods(self, namespace: str, selector: dict[str, str]) -> list[Pod]: ...
    def create_service(self, svc: Service) -> None: ...
    def delete_service(self, namespace: str, name: str) -> None: ...
    def get_service(self, namespace: str, name: str) -> Optional[Service]: ...
    def resolve(self, namespace: str, service: str) -> str:
        """DNS-equivalent: service name -> address workers can dial."""
        ...


def admit_pod(cluster: Cluster, pod: Pod) -> None:
    """Admit a pod: mark it schedulable and invoke the backend's start hook
    where one exists — LocalProcessCluster launches the process, KubeCluster
    lifts the gang gate (gang pods) and publishes late-bound env,
    FakeCluster has no hook (tests play kubelet via
    set_phase/run_scheduled). Both the job reconciler (post-gang-admission)
    and the Deployment-style controllers (serving/notebook/tensorboard,
    no gang barrier) route through this one contract."""
    pod.scheduled = True
    start = getattr(cluster, "start_pod", None)
    if start is not None:
        start(pod)


def create_and_admit(cluster: Cluster, pod: Pod) -> None:
    """Deployment-style pod creation: create + immediately admit. A lost
    create race (another reconcile pass — or, on kube, a lagging informer
    briefly hiding a live pod — already made it) adopts instead of
    raising: the pod exists, which is all the caller wanted."""
    try:
        cluster.create_pod(pod)
    except KeyError:
        return
    admit_pod(cluster, pod)


class FakeCluster:
    """In-memory cluster; tests drive pod phases via `set_phase`."""

    def __init__(self):
        self.pods: dict[tuple[str, str], Pod] = {}
        self.services: dict[tuple[str, str], Service] = {}
        self.events: list[str] = []

    def create_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        if key in self.pods:
            raise KeyError(f"pod {key} exists")
        self.pods[key] = pod
        self.events.append(f"create_pod {pod.name}")

    def delete_pod(self, namespace: str, name: str) -> None:
        self.pods.pop((namespace, name), None)
        self.events.append(f"delete_pod {name}")

    def get_pod(self, namespace, name):
        return self.pods.get((namespace, name))

    def list_pods(self, namespace, selector):
        return [
            p for (ns, _), p in self.pods.items()
            if ns == namespace and all(p.labels.get(k) == v for k, v in selector.items())
        ]

    def create_service(self, svc: Service) -> None:
        self.services[(svc.namespace, svc.name)] = svc

    def delete_service(self, namespace, name):
        self.services.pop((namespace, name), None)

    def get_service(self, namespace, name):
        return self.services.get((namespace, name))

    def resolve(self, namespace, service):
        svc = self.services[(namespace, service)]
        return f"{service}.{namespace}.svc:{svc.port}"

    # -- test helpers (the 'kubelet' role) --
    def set_phase(self, namespace, name, phase, exit_code=None):
        pod = self.pods[(namespace, name)]
        pod.phase = phase
        pod.exit_code = exit_code

    def run_scheduled(self):
        """Pretend kubelet: move every scheduled Pending pod to Running."""
        for pod in self.pods.values():
            if pod.phase == PodPhase.PENDING and pod.scheduled:
                pod.phase = PodPhase.RUNNING


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalProcessCluster:
    """Pods are real subprocesses; the e2e path (SURVEY.md §4.3's kind-cluster
    analogue). `command` runs with the pod env merged over os.environ."""

    def __init__(self, log_dir: str = "/tmp/kft-pods"):
        self.pods: dict[tuple[str, str], Pod] = {}
        self.procs: dict[tuple[str, str], subprocess.Popen] = {}
        self.init_procs: dict[tuple[str, str], subprocess.Popen] = {}
        self.services: dict[tuple[str, str], Service] = {}
        self.ports: dict[tuple[str, str], int] = {}
        self.log_dir = log_dir
        self._lock = threading.Lock()   # pods/procs dicts vs async init
        self._starting: set[tuple[str, str]] = set()   # start_pod in flight
        os.makedirs(log_dir, exist_ok=True)

    def create_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        if key in self.pods:
            raise KeyError(f"pod {key} exists")
        self.pods[key] = pod

    def start_pod(self, pod: Pod) -> None:
        """Launch the process (called at admission). Idempotent: a pod whose
        process (or init step) is already launched is left alone — repeated
        reconcile passes admit the same pod more than once."""
        key = (pod.namespace, pod.name)
        with self._lock:
            if pod.phase != PodPhase.PENDING:
                return      # terminal pods restart via delete+recreate only
            if key in self.procs or key in self.init_procs \
                    or key in self._starting:
                return
            self._starting.add(key)
        env = dict(os.environ)
        env.update(pod.env)
        log = open(os.path.join(self.log_dir, f"{pod.name}.log"), "wb")

        def _launch():
            # caller holds self._lock (or no init thread exists yet).
            # A failed spawn (bad command, ENOMEM) marks the pod FAILED —
            # never leaves it wedged Pending with a stuck _starting entry
            try:
                proc = subprocess.Popen(
                    pod.command or [sys.executable, "-c", "pass"],
                    env=env, stdout=log, stderr=subprocess.STDOUT,
                )
            except OSError as e:
                self._starting.discard(key)
                pod.phase = PodPhase.FAILED
                pod.exit_code = -1
                log.write(f"spawn failed: {e}\n".encode())
                log.close()
                return
            self.procs[key] = proc
            self._starting.discard(key)     # outcome recorded in procs
            pod.phase = PodPhase.RUNNING
            pod.node = "localhost"

        if pod.init_command:
            # initContainer semantics: pod stays Pending while the init step
            # runs (async — a slow storage download must not block the
            # reconcile loop), then the main command starts. The lock closes
            # the race with delete_pod: a deleted pod's init is killed and
            # its main command never launches.
            def _init_then_launch():
                try:
                    init = subprocess.Popen(
                        pod.init_command, env=env, stdout=log,
                        stderr=subprocess.STDOUT)
                except OSError as e:
                    with self._lock:
                        self._starting.discard(key)
                        pod.phase = PodPhase.FAILED
                        pod.exit_code = -1
                        log.write(f"init spawn failed: {e}\n".encode())
                        log.close()
                    return
                with self._lock:
                    if key not in self.pods:
                        init.kill()
                        log.close()
                        self._starting.discard(key)
                        return
                    self.init_procs[key] = init
                    self._starting.discard(key)  # in-flight now visible
                rc = init.wait()
                with self._lock:
                    self.init_procs.pop(key, None)
                    if key not in self.pods:
                        log.close()
                        return
                    if rc != 0:
                        pod.phase = PodPhase.FAILED
                        pod.exit_code = rc
                        log.close()
                        return
                    _launch()

            threading.Thread(target=_init_then_launch, daemon=True).start()
        else:
            with self._lock:
                _launch()

    def delete_pod(self, namespace, name):
        key = (namespace, name)
        with self._lock:
            init = self.init_procs.pop(key, None)
            proc = self.procs.pop(key, None)
            self.pods.pop(key, None)
            self._starting.discard(key)
        if init and init.poll() is None:
            init.kill()
        if proc and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def get_pod(self, namespace, name):
        key = (namespace, name)
        pod = self.pods.get(key)
        if pod is None:
            return None
        proc = self.procs.get(key)
        if proc is not None and pod.phase == PodPhase.RUNNING:
            rc = proc.poll()
            if rc is not None:
                pod.exit_code = rc
                pod.phase = PodPhase.SUCCEEDED if rc == 0 else PodPhase.FAILED
        return pod

    def list_pods(self, namespace, selector):
        return [
            self.get_pod(ns, n) for (ns, n) in list(self.pods)
            if ns == namespace and all(
                self.pods[(ns, n)].labels.get(k) == v for k, v in selector.items()
            )
        ]

    def create_service(self, svc: Service) -> None:
        key = (svc.namespace, svc.name)
        port = _free_port()
        self.ports[key] = port
        self.services[key] = svc

    def delete_service(self, namespace, name):
        self.services.pop((namespace, name), None)
        self.ports.pop((namespace, name), None)

    def get_service(self, namespace, name):
        return self.services.get((namespace, name))

    def allocate_port(self) -> int:
        """Per-pod port allocation — the pod-IP analogue on one machine.
        Controllers stamp each pod's bind address with this so replicas
        never collide on a port."""
        return _free_port()

    def resolve(self, namespace, service):
        # Endpoint semantics: a Service resolves to a RUNNING pod matching
        # its selector (via the pod's stamped bind address); fall back to
        # the service's own allocated port when no endpoint is up yet.
        svc = self.services.get((namespace, service))
        if svc is not None:
            for pod in self.list_pods(namespace, svc.selector):
                if pod is not None and pod.phase == PodPhase.RUNNING \
                        and pod.env.get("KFT_BIND"):
                    return pod.env["KFT_BIND"]
        return f"127.0.0.1:{self.ports[(namespace, service)]}"

    def pod_log(self, namespace: str, name: str) -> str:
        path = os.path.join(self.log_dir, f"{name}.log")
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def shutdown(self):
        for key in list(self.pods):    # pods, not procs: reaps mid-init pods
            self.delete_pod(*key)
