"""Cluster abstraction the job controller reconciles against.

The reference controller talks to the Kubernetes apiserver via client-go
informers (SURVEY.md §3.1). Here the same reconcile logic runs over a small
`Cluster` interface with three implementations:

- `FakeCluster` — in-memory pods/services whose phases tests drive by hand;
  the envtest equivalent (SURVEY.md §4.2: 'pods are created but never run').
- `LocalProcessCluster` — pods are real OS processes on this machine;
  headless services resolve to 127.0.0.1 ports. This gives REAL
  jax.distributed multi-process rendezvous in CI without a cluster.
- `ManifestCluster` — renders Kubernetes YAML (Pod/Service/PodGroup with GKE
  TPU node selectors) for a real deployment; no cluster needed to test the
  rendering.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Optional, Protocol


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class Pod:
    name: str
    namespace: str
    labels: dict[str, str]
    env: dict[str, str]
    command: list[str]
    # initContainer role: runs to completion before `command` starts (the
    # reference's storage-initializer injection, SURVEY.md §2.4)
    init_command: list[str] = dataclasses.field(default_factory=list)
    phase: PodPhase = PodPhase.PENDING
    exit_code: Optional[int] = None
    node: Optional[str] = None
    scheduled: bool = False            # gang admission happened
    # gang-scheduled pods carry a scheduling gate on real backends until the
    # whole slice group is admitted (the job reconciler's whole-slice atom);
    # Deployment-style pods (serving/notebook/tensorboard) never gate — they
    # schedule individually the moment they are admitted
    gang: bool = False
    created_at: float = dataclasses.field(default_factory=time.time)
    # real-cluster placement (rendered by the KubeCluster backend; ignored
    # by in-memory/local-process backends): container image, GKE TPU
    # topology nodeSelector, and resource limits (google.com/tpu etc.)
    image: str = ""
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    resources: dict[str, str] = dataclasses.field(default_factory=dict)
    # server-side annotations mirror (kube backend): mutable metadata that
    # changes at runtime — late-bound env, zygote address, the elastic
    # restart-epoch signal the kubelet acts on. Backends without an
    # apiserver leave it empty.
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Service:
    name: str
    namespace: str
    selector: dict[str, str]
    port: int


class Cluster(Protocol):
    def create_pod(self, pod: Pod) -> None: ...
    def delete_pod(self, namespace: str, name: str) -> None: ...
    def get_pod(self, namespace: str, name: str) -> Optional[Pod]: ...
    def list_pods(self, namespace: str, selector: dict[str, str]) -> list[Pod]: ...
    def create_service(self, svc: Service) -> None: ...
    def delete_service(self, namespace: str, name: str) -> None: ...
    def get_service(self, namespace: str, name: str) -> Optional[Service]: ...
    def resolve(self, namespace: str, service: str) -> str:
        """DNS-equivalent: service name -> address workers can dial."""
        ...


def admit_pod(cluster: Cluster, pod: Pod) -> None:
    """Admit a pod: mark it schedulable and invoke the backend's start hook
    where one exists — LocalProcessCluster launches the process, KubeCluster
    lifts the gang gate (gang pods) and publishes late-bound env,
    FakeCluster has no hook (tests play kubelet via
    set_phase/run_scheduled). Both the job reconciler (post-gang-admission)
    and the Deployment-style controllers (serving/notebook/tensorboard,
    no gang barrier) route through this one contract."""
    pod.scheduled = True
    start = getattr(cluster, "start_pod", None)
    if start is not None:
        start(pod)


def allocate_bind(cluster: Cluster) -> Optional[str]:
    """Per-pod bind address on image-less backends: clusters with an
    ``allocate_port`` hook (local processes sharing one host) get a
    distinct ``127.0.0.1:port`` per pod — the pod-IP analogue. Returns
    None on real-cluster backends (pods bind their container port)."""
    alloc = getattr(cluster, "allocate_port", None)
    return f"127.0.0.1:{alloc()}" if alloc is not None else None


def create_and_admit(cluster: Cluster, pod: Pod) -> None:
    """Deployment-style pod creation: create + immediately admit. A lost
    create race (another reconcile pass — or, on kube, a lagging informer
    briefly hiding a live pod — already made it) adopts instead of
    raising: the pod exists, which is all the caller wanted."""
    try:
        cluster.create_pod(pod)
    except KeyError:
        return
    admit_pod(cluster, pod)


class FakeCluster:
    """In-memory cluster; tests drive pod phases via `set_phase`."""

    def __init__(self):
        self.pods: dict[tuple[str, str], Pod] = {}
        self.services: dict[tuple[str, str], Service] = {}
        self.events: list[str] = []

    def create_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        if key in self.pods:
            raise KeyError(f"pod {key} exists")
        self.pods[key] = pod
        self.events.append(f"create_pod {pod.name}")

    def delete_pod(self, namespace: str, name: str) -> None:
        self.pods.pop((namespace, name), None)
        self.events.append(f"delete_pod {name}")

    def get_pod(self, namespace, name):
        return self.pods.get((namespace, name))

    def list_pods(self, namespace, selector):
        return [
            p for (ns, _), p in self.pods.items()
            if ns == namespace and all(p.labels.get(k) == v for k, v in selector.items())
        ]

    def create_service(self, svc: Service) -> None:
        self.services[(svc.namespace, svc.name)] = svc

    def delete_service(self, namespace, name):
        self.services.pop((namespace, name), None)

    def get_service(self, namespace, name):
        return self.services.get((namespace, name))

    def resolve(self, namespace, service):
        svc = self.services[(namespace, service)]
        return f"{service}.{namespace}.svc:{svc.port}"

    def restart_pod_process(self, namespace: str, name: str,
                            env_updates: Optional[dict] = None) -> bool:
        """Re-rendezvous signal (elastic recovery): restart the pod's
        process IN PLACE — the pod object, its labels, and its scheduling
        survive. In-memory pods have no process; the env update and the
        event record are what tests assert."""
        pod = self.pods.get((namespace, name))
        if pod is None or pod.phase not in (PodPhase.PENDING,
                                            PodPhase.RUNNING):
            return False
        pod.env.update(env_updates or {})
        # the pod's PROCESS incarnation restarted now: created_at is what
        # heartbeat staleness measures startup grace from, and the old
        # incarnation's last beat must read as "never beat yet", not as a
        # 60s-stale beat that insta-fails the survivor mid-recovery
        pod.created_at = time.time()
        self.events.append(f"restart_pod_process {name}")
        return True

    # -- test helpers (the 'kubelet' role) --
    def set_phase(self, namespace, name, phase, exit_code=None):
        pod = self.pods[(namespace, name)]
        pod.phase = phase
        pod.exit_code = exit_code

    def run_scheduled(self):
        """Pretend kubelet: move every scheduled Pending pod to Running."""
        for pod in self.pods.values():
            if pod.phase == PodPhase.PENDING and pod.scheduled:
                pod.phase = PodPhase.RUNNING


def zygote_eligible(command: list[str]) -> bool:
    """True when ``command`` is the ``[sys.executable, -m, module, ...]``
    form a zygote can fork (rendezvous/zygote.py protocol). ONE rule shared
    by the local warm pool and the kube WarmPoolController, so the two
    backends can never silently disagree about what warm-starts."""
    return (len(command) >= 3 and command[0] == sys.executable
            and command[1] == "-m")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _ZygoteProc:
    """Popen-shaped handle for a zygote-forked pod: liveness and exit code
    arrive over the held-open socket connection (the zygote is the real
    parent and reaps the child)."""

    def __init__(self, conn, pid: int, pending: bytes = b""):
        self._conn = conn
        self.pid = pid
        self.returncode: Optional[int] = None
        self._done = threading.Event()
        self._pending = pending          # bytes read past the pid message
        threading.Thread(target=self._reader, daemon=True).start()

    def _reader(self):
        buf = self._pending
        try:
            while b"\n" not in buf:
                chunk = self._conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
            import json as _json

            self.returncode = int(_json.loads(buf.split(b"\n", 1)[0])["exit"])
        except Exception:
            # zygote died (EOF / garbage): its children are reparented to
            # init and may still be running — kill ours before reporting,
            # or shutdown() would leave a live orphan it believes dead
            if self.returncode is None:
                try:
                    os.kill(self.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                self.returncode = -1
        finally:
            self._done.set()
            try:
                self._conn.close()
            except OSError:
                pass

    def poll(self) -> Optional[int]:
        return self.returncode

    def send_signal(self, sig) -> None:
        if self.returncode is None:
            try:
                os.kill(self.pid, sig)
            except ProcessLookupError:
                pass

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)

    def wait(self, timeout: Optional[float] = None) -> int:
        if not self._done.wait(timeout):
            raise subprocess.TimeoutExpired("zygote-pod", timeout)
        return self.returncode


class LocalProcessCluster:
    """Pods are real subprocesses; the e2e path (SURVEY.md §4.3's kind-cluster
    analogue). `command` runs with the pod env merged over os.environ.

    ``warm_pool=True`` starts a pre-imported zygote
    (rendezvous/zygote.py): pods whose command is the
    ``[sys.executable, "-m", module, ...]`` form fork from it instead of
    paying a cold interpreter + jax import — the submit→first-step
    latency lever (BASELINE.md row 2). Anything else falls back to a
    plain spawn."""

    def __init__(self, log_dir: str = "/tmp/kft-pods",
                 warm_pool: bool = False,
                 depot_dir: Optional[str] = None):
        self.pods: dict[tuple[str, str], Pod] = {}
        self.procs: dict[tuple[str, str], subprocess.Popen] = {}
        self.init_procs: dict[tuple[str, str], subprocess.Popen] = {}
        self.services: dict[tuple[str, str], Service] = {}
        self.ports: dict[tuple[str, str], int] = {}
        self.log_dir = log_dir
        self._lock = threading.Lock()   # pods/procs dicts vs async init
        self._starting: set[tuple[str, str]] = set()   # start_pod in flight
        self.warm_pool = warm_pool
        self._zygote: Optional[subprocess.Popen] = None
        self._zygote_sock: Optional[str] = None
        self._zygote_lock = threading.Lock()
        # observability: pods that wanted the warm pool but cold-spawned —
        # an entrypoint rename silently regressing submit latency is
        # exactly the kind of thing this counter surfaces (bench reads it)
        self.zygote_fallbacks = 0
        # executable depot (parallel/depot.py, shared-directory form):
        # pods on this backend share a filesystem, so compile-once is one
        # directory away. warm_pool implies it — both are the same
        # submit→first-step lever; an Operator-injected KFT_DEPOT (its
        # pod mutator runs first) takes precedence via setdefault.
        if depot_dir is None and warm_pool:
            depot_dir = os.path.join(log_dir, "depot")
        self.depot_dir = depot_dir
        if depot_dir:
            os.makedirs(depot_dir, exist_ok=True)
        os.makedirs(log_dir, exist_ok=True)
        if warm_pool:
            # eager, non-blocking spawn: the zygote imports while the
            # daemon boots, so the first pod already finds it ready
            self._ensure_zygote(wait_s=0)

    # ------------------------------------------------------ warm pool --

    def _ensure_zygote(self, wait_s: float = 3.0) -> Optional[str]:
        """Start (once) and health-check the zygote; -> socket path or
        None when not ready within ``wait_s`` (caller falls back to a
        plain spawn — a pod launch must never block minutes on the
        optimization; later pods pick the zygote up once it binds).
        A deliberate pre-warm (bench/daemon startup) passes a long wait."""
        with self._zygote_lock:
            if self._zygote is None or self._zygote.poll() is not None:
                sock = os.path.join(self.log_dir, "zygote.sock")
                try:
                    os.unlink(sock)     # a stale socket is not readiness
                except FileNotFoundError:
                    pass
                log = open(os.path.join(self.log_dir, "zygote.log"), "wb")
                try:
                    self._zygote = subprocess.Popen(
                        [sys.executable, "-m",
                         "kubeflow_tpu.rendezvous.zygote", sock],
                        stdout=log, stderr=subprocess.STDOUT)
                except OSError:
                    return None
                self._zygote_sock = sock
            deadline = time.time() + wait_s
            while time.time() < deadline:
                if os.path.exists(self._zygote_sock):
                    return self._zygote_sock
                if self._zygote.poll() is not None:
                    return None
                time.sleep(0.05)
            return None

    def _zygote_spawn(self, pod: Pod, env: dict,
                      log_path: str) -> Optional[_ZygoteProc]:
        import json as _json
        import socket as _socket

        sock_path = self._ensure_zygote()
        if sock_path is None:
            return None
        try:
            conn = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            conn.connect(sock_path)
            conn.sendall(_json.dumps(
                {"argv": pod.command, "env": env, "log": log_path}
            ).encode() + b"\n")
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("zygote hung up")
                buf += chunk
            # a fast-exiting child can coalesce the pid and exit messages
            # into one read: frame at the FIRST newline, hand the rest to
            # the exit reader
            line, rest = buf.split(b"\n", 1)
            return _ZygoteProc(conn, int(_json.loads(line)["pid"]),
                               pending=rest)
        except (OSError, ValueError, KeyError):
            return None

    def create_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        if key in self.pods:
            raise KeyError(f"pod {key} exists")
        self.pods[key] = pod

    def start_pod(self, pod: Pod) -> None:
        """Launch the process (called at admission). Idempotent: a pod whose
        process (or init step) is already launched is left alone — repeated
        reconcile passes admit the same pod more than once."""
        key = (pod.namespace, pod.name)
        with self._lock:
            if pod.phase != PodPhase.PENDING:
                return      # terminal pods restart via delete+recreate only
            if key in self.procs or key in self.init_procs \
                    or key in self._starting:
                return
            self._starting.add(key)
        if self.depot_dir:
            pod.env.setdefault("KFT_DEPOT", self.depot_dir)
        env = dict(os.environ)
        env.update(pod.env)
        log = open(os.path.join(self.log_dir, f"{pod.name}.log"), "wb")

        log_path = os.path.join(self.log_dir, f"{pod.name}.log")

        def _launch():
            # caller holds self._lock (or no init thread exists yet).
            # A failed spawn (bad command, ENOMEM) marks the pod FAILED —
            # never leaves it wedged Pending with a stuck _starting entry
            proc = None
            if self.warm_pool:
                eligible = zygote_eligible(pod.command)
                if eligible:
                    proc = self._zygote_spawn(pod, dict(pod.env), log_path)
                if proc is not None:
                    log.close()             # the forked child owns its fd
                else:
                    # cold spawn despite warm_pool: say so, loudly enough
                    # to find (pod log + counter), quietly enough to run
                    self.zygote_fallbacks += 1
                    reason = (
                        "command is not [sys.executable, -m, module]"
                        if not eligible
                        else "zygote spawn failed (not ready, or RPC error"
                             " — see zygote log)")
                    log.write(
                        f"warm-pool fallback: {reason}; cold spawn of "
                        f"{pod.command!r}\n".encode())
                    log.flush()
            if proc is None:
                try:
                    proc = subprocess.Popen(
                        pod.command or [sys.executable, "-c", "pass"],
                        env=env, stdout=log, stderr=subprocess.STDOUT,
                    )
                except OSError as e:
                    self._starting.discard(key)
                    pod.phase = PodPhase.FAILED
                    pod.exit_code = -1
                    log.write(f"spawn failed: {e}\n".encode())
                    log.close()
                    return
            self.procs[key] = proc
            self._starting.discard(key)     # outcome recorded in procs
            pod.phase = PodPhase.RUNNING
            pod.node = "localhost"

        if pod.init_command:
            # initContainer semantics: pod stays Pending while the init step
            # runs (async — a slow storage download must not block the
            # reconcile loop), then the main command starts. The lock closes
            # the race with delete_pod: a deleted pod's init is killed and
            # its main command never launches.
            def _init_then_launch():
                try:
                    init = subprocess.Popen(
                        pod.init_command, env=env, stdout=log,
                        stderr=subprocess.STDOUT)
                except OSError as e:
                    with self._lock:
                        self._starting.discard(key)
                        pod.phase = PodPhase.FAILED
                        pod.exit_code = -1
                        log.write(f"init spawn failed: {e}\n".encode())
                        log.close()
                    return
                with self._lock:
                    if key not in self.pods:
                        init.kill()
                        log.close()
                        self._starting.discard(key)
                        return
                    self.init_procs[key] = init
                    self._starting.discard(key)  # in-flight now visible
                rc = init.wait()
                with self._lock:
                    self.init_procs.pop(key, None)
                    if key not in self.pods:
                        log.close()
                        return
                    if rc != 0:
                        pod.phase = PodPhase.FAILED
                        pod.exit_code = rc
                        log.close()
                        return
                    _launch()

            threading.Thread(target=_init_then_launch, daemon=True).start()
        else:
            with self._lock:
                _launch()

    def restart_pod_process(self, namespace: str, name: str,
                            env_updates: Optional[dict] = None) -> bool:
        """Re-rendezvous signal (elastic recovery): kill the pod's process
        and start a fresh one IN the same pod — name, labels, gang
        admission, log file, and node-local caches all survive; only the
        process (and so its jax.distributed world membership) is new. The
        restarted process forks from the zygote when eligible, so the
        survivor's bounce is warm too."""
        with self._lock:
            key = (namespace, name)
            pod = self.pods.get(key)
            proc = self.procs.pop(key, None)
            if pod is None or proc is None:
                if proc is not None:        # pod gone: don't leak the proc
                    self.procs[key] = proc
                return False
            pod.env.update(env_updates or {})
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        env = dict(os.environ)
        env.update(pod.env)
        log_path = os.path.join(self.log_dir, f"{pod.name}.log")
        log = open(log_path, "ab")
        log.write(b"restart_pod_process: re-rendezvous signal\n")
        log.flush()
        with self._lock:
            if key not in self.pods:        # deleted while we were killing
                log.close()
                return False
            # new process incarnation: restart the heartbeat grace clock
            # (see FakeCluster.restart_pod_process)
            pod.created_at = time.time()
            proc = None
            if self.warm_pool and zygote_eligible(pod.command):
                proc = self._zygote_spawn(pod, dict(pod.env), log_path)
            if proc is not None:
                log.close()
            else:
                if self.warm_pool:
                    self.zygote_fallbacks += 1
                try:
                    proc = subprocess.Popen(
                        pod.command or [sys.executable, "-c", "pass"],
                        env=env, stdout=log, stderr=subprocess.STDOUT)
                except OSError as e:
                    pod.phase = PodPhase.FAILED
                    pod.exit_code = -1
                    log.write(f"restart spawn failed: {e}\n".encode())
                    log.close()
                    return False
            self.procs[key] = proc
            pod.phase = PodPhase.RUNNING
            return True

    def delete_pod(self, namespace, name):
        key = (namespace, name)
        with self._lock:
            init = self.init_procs.pop(key, None)
            proc = self.procs.pop(key, None)
            self.pods.pop(key, None)
            self._starting.discard(key)
        if init and init.poll() is None:
            init.kill()
        if proc and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def get_pod(self, namespace, name):
        key = (namespace, name)
        pod = self.pods.get(key)
        if pod is None:
            return None
        proc = self.procs.get(key)
        if proc is not None and pod.phase == PodPhase.RUNNING:
            rc = proc.poll()
            if rc is not None:
                pod.exit_code = rc
                pod.phase = PodPhase.SUCCEEDED if rc == 0 else PodPhase.FAILED
        return pod

    def list_pods(self, namespace, selector):
        return [
            self.get_pod(ns, n) for (ns, n) in list(self.pods)
            if ns == namespace and all(
                self.pods[(ns, n)].labels.get(k) == v for k, v in selector.items()
            )
        ]

    def create_service(self, svc: Service) -> None:
        key = (svc.namespace, svc.name)
        port = _free_port()
        self.ports[key] = port
        self.services[key] = svc

    def delete_service(self, namespace, name):
        self.services.pop((namespace, name), None)
        self.ports.pop((namespace, name), None)

    def get_service(self, namespace, name):
        return self.services.get((namespace, name))

    def allocate_port(self) -> int:
        """Per-pod port allocation — the pod-IP analogue on one machine.
        Controllers stamp each pod's bind address with this so replicas
        never collide on a port."""
        return _free_port()

    def resolve(self, namespace, service):
        # Endpoint semantics: a Service resolves to a RUNNING pod matching
        # its selector (via the pod's stamped bind address); fall back to
        # the service's own allocated port when no endpoint is up yet.
        svc = self.services.get((namespace, service))
        if svc is not None:
            for pod in self.list_pods(namespace, svc.selector):
                if pod is not None and pod.phase == PodPhase.RUNNING \
                        and pod.env.get("KFT_BIND"):
                    return pod.env["KFT_BIND"]
        return f"127.0.0.1:{self.ports[(namespace, service)]}"

    def pod_log(self, namespace: str, name: str) -> str:
        path = os.path.join(self.log_dir, f"{name}.log")
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def shutdown(self):
        for key in list(self.pods):    # pods, not procs: reaps mid-init pods
            self.delete_pod(*key)
        with self._zygote_lock:
            if self._zygote is not None and self._zygote.poll() is None:
                self._zygote.kill()
                self._zygote.wait(timeout=5)
            self._zygote = None
