"""Warm-pool subsystem for the Kube backend: pre-warmed standby pods.

The submit→first-step levers (fork zygote, persistent compile cache)
existed only on ``LocalProcessCluster`` — the backend that represents
production had none (VERDICT r5 Missing #3). This module is the kube
analogue of ``warm_pool=True``, shaped like Podracer-style systems that
keep accelerator workers hot and RE-TARGET them instead of cold-starting
(PAPERS.md: Podracer architectures; TPU concurrency studies show startup
and dispatch, not math, dominate small-step regimes):

- ``WarmPoolController`` reconciles a target population of STANDBY pods
  per pool class (pool size / class keys / reap policy from
  ``platform/config.py``). Each standby pod runs a node-resident zygote
  (``rendezvous/zygote.py`` in its ``tcp://`` form) with the heavy
  imports done and the XLA compile cache mounted; the node agent
  publishes the zygote's bound address as a pod annotation.
- Job admission (``KubeCluster.start_pod``) CLAIMS a standby pod instead
  of scheduling the cold one: a compare-and-swap label patch (the
  apiserver 409s a stale resourceVersion, so a race over the last warm
  pod has exactly one winner) moves the pod into the gang's label
  selector, the late-bound worker env travels in the exec request, and
  the worker argv is delivered to the resident zygote over the pod
  network — fork in milliseconds, no interpreter, no ``import jax``.
- A dry pool (or a zygote that died between claim and use) falls back to
  the normal cold path, COUNTED (``fallbacks`` / ``dead_claims``), like
  ``cluster.zygote_fallbacks`` on the local backend — a silently dead
  pool must regress visibly, never quietly.
- The controller replenishes the pool asynchronously (the operator ticks
  ``reconcile()``) and reaps consumed/terminal/expired standby pods.

Because Kubernetes pods cannot be renamed, a claimed pod keeps its own
name and the job pod name ALIASES to it (``KubeCluster._claims``,
rebuilt after a controller restart from the ``claimed-as`` annotation).
"""

from __future__ import annotations

import json
import socket
import sys
import threading
import time
from typing import Optional

from kubeflow_tpu.controller.cluster import (
    Pod, PodPhase, create_and_admit, zygote_eligible,
)
from kubeflow_tpu.controller.kube import (
    CLAIMED_AS_ANNOTATION, ENV_ANNOTATION_PREFIX, KubeApiError,
)

POOL_CLASS_LABEL = "kubeflow-tpu.org/warm-pool"    # value: pool class key
POOL_STATE_LABEL = "kubeflow-tpu.org/warm-state"   # "standby" | "claimed"
ZYGOTE_ADDR_ANNOTATION = "kubeflow-tpu.org/zygote-addr"
# the ROTATED exec token after a reclaim. Pod spec env is immutable, so a
# reclaimed pod's fresh token cannot live where the original did — it is
# published as an annotation, and _try_claim prefers it over the spec env.
# Same trust domain either way: reading annotations needs apiserver
# pod-read rights, which already imply claim rights.
ZYGOTE_TOKEN_ANNOTATION = "kubeflow-tpu.org/zygote-token"
ZYGOTE_PORT = 8479          # the fixed containerPort on a real cluster

_TERMINAL = (PodPhase.SUCCEEDED, PodPhase.FAILED)


def default_zygote_command() -> list[str]:
    """Standby pod main command: a TCP zygote on the conventional
    containerPort (pods have distinct IPs on a real cluster, so a fixed
    port is safe and lets the controller dial pod_ip:8479 directly).
    Image-less single-host environments (FakeKubelet) must pass
    ``tcp://127.0.0.1:0`` instead — every standby shares one host there,
    and the announce contract carries the ephemeral port back."""
    return [sys.executable, "-m", "kubeflow_tpu.rendezvous.zygote",
            f"tcp://0.0.0.0:{ZYGOTE_PORT}"]


class _ClaimWatcher(threading.Thread):
    """Holds the claim connection for the life of the forked worker and
    plays the container-status reporter: when the zygote reports the
    worker's exit (or dies — EOF), the pod's phase is PATCHed terminal.
    On a real cluster a thin in-pod shim could own this; in this
    single-binary architecture the claimant operator does."""

    def __init__(self, cluster, namespace: str, name: str, conn,
                 pending: bytes = b""):
        super().__init__(daemon=True, name=f"warm-claim-{name}")
        self.cluster = cluster
        self.namespace = namespace
        self.pod_name = name
        self.conn = conn
        self.pending = pending
        self.exit_code: Optional[int] = None
        # reclaim handshake: disarm() and the terminal report race over
        # one lock, so exactly ONE of them wins — either the worker's
        # exit marks the pod terminal, or the reclaim suppresses that
        # and the pod goes back to standby. Never both.
        self._report_lock = threading.Lock()
        self._disarmed = False
        self.reported = False

    def disarm(self) -> bool:
        """Suppress the terminal phase report (reclaim path). Returns
        True if disarmed BEFORE any report — the reclaim may proceed;
        False if the exit was already reported — the worker finished
        first, the pod is terminal, and the reclaim must no-op."""
        with self._report_lock:
            if self.reported:
                return False
            self._disarmed = True
            return True

    def run(self) -> None:
        buf = self.pending
        try:
            while b"\n" not in buf:
                chunk = self.conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
            self.exit_code = int(json.loads(buf.split(b"\n", 1)[0])["exit"])
        except Exception:
            # zygote died mid-run: PDEATHSIG killed the worker with it
            self.exit_code = -1
        finally:
            try:
                self.conn.close()
            except OSError:
                pass
            phase = (PodPhase.SUCCEEDED if self.exit_code == 0
                     else PodPhase.FAILED)
            with self._report_lock:
                if self._disarmed:
                    # reclaimed mid-run: the zygote killed the worker and
                    # the pod is headed back to standby — a terminal PATCH
                    # here would wedge it (terminal-wins, never resurrected)
                    return
                try:
                    self.cluster.set_phase(
                        self.namespace, self.pod_name, phase,
                        self.exit_code)
                except Exception:
                    pass    # apiserver gone (shutdown): nothing to report to
                self.reported = True


class WarmPoolController:
    """Reconciles standby zygote pods and claims them at job admission.

    Attach with ``cluster.warm_pool = pool`` (``KubeCluster.start_pod``
    consults it); tick ``reconcile()`` from the operator's serving loop.
    All counters are monotonic and exported by the operator as
    ``kft_warm_pool_*`` metrics — and by bench.py into BENCH JSON.
    """

    def __init__(self, cluster, *, namespace: str = "default",
                 size: int = 1, classes=("default",),
                 reap_s: float = 600.0, image: str = "",
                 command: Optional[list[str]] = None,
                 env: Optional[dict] = None,
                 name_prefix: str = "kft-warm",
                 dial_timeout_s: float = 3.0):
        self.cluster = cluster
        self.namespace = namespace
        self.size = int(size)
        self.classes = list(classes)
        self.reap_s = float(reap_s)
        self.image = image
        self.command = list(command or default_zygote_command())
        self.env = dict(env or {})
        self.name_prefix = name_prefix
        self.dial_timeout_s = dial_timeout_s
        self._lock = threading.Lock()
        self._seq = 0
        # observability (see module docstring: dead pools must be loud)
        self.claims = 0          # warm pods claimed into gangs
        self.fallbacks = 0       # eligible pods that cold-started anyway
        self.dead_claims = 0     # claims lost to a dead zygote
        self.claim_errors = 0    # non-conflict apiserver/dial failures
        self.created = 0
        self.reaped = 0
        # executable-depot pre-fetch at claim time (parallel/depot.py):
        # entries synced into the claimed pod's local cache before the
        # worker forks, so its compile phase is a cache read
        self.prefetched_entries = 0
        self.prefetch_errors = 0
        # reclaim arc (claimed -> running -> reclaimed -> claimable):
        # early-stopped trials RETURN their pod instead of deleting it
        self.reclaims = 0        # pods returned to standby, re-claimable
        self.reclaim_noops = 0   # reclaim of a finished/dead/gone pod
        # live claim watchers by claimed pod key — reclaim must disarm
        # the exit reporter before the zygote kills the worker, or the
        # kill itself would mark the returning pod terminal
        self._watchers: dict = {}

    # ------------------------------------------------------ eligibility --

    def eligible(self, pod: Pod) -> bool:
        """Gang (job) pods AND serving predictor replicas with a
        zygote-forkable command claim from the pool — a fleet scale-up
        replica must fork pre-imported, not pay a cold interpreter.
        Pods with an init step (storage initializer) must cold-start:
        the zygote only execs the main command. Notebook/transformer/
        explainer pods keep their own lifecycle."""
        if not zygote_eligible(pod.command) or pod.init_command:
            return False
        return pod.gang or pod.labels.get("component") == "predictor"

    @staticmethod
    def pool_class_for(pod: Pod) -> str:
        """Pool class key: the TPU accelerator the pod schedules onto
        (a v5p job must claim a v5p-resident zygote), else "default"."""
        accel = pod.node_selector.get(
            "cloud.google.com/gke-tpu-accelerator", "")
        return accel[len("tpu-"):] if accel.startswith("tpu-") else "default"

    # -------------------------------------------------------- reconcile --

    def reconcile(self) -> None:
        """Converge each class to ``size`` live standby pods: reap
        terminal/expired standbys and consumed (claimed, terminal) pods,
        then create what is missing. Idempotent; safe to tick often."""
        now = time.time()
        for cls in self.classes:
            live = 0
            for pod in self._pool_pods(cls, "standby"):
                if pod is None:
                    continue
                if pod.phase in _TERMINAL or (
                        now - pod.created_at > self.reap_s):
                    self._reap(pod)
                else:
                    live += 1
            for pod in self._pool_pods(cls, "claimed"):
                # a consumed pod (worker exited) is done serving its job;
                # reap ONLY after the job no longer selects it (clean-pod
                # policy may want the terminal pod around briefly — reap
                # on the expiry clock like any other pool member)
                if pod is not None and pod.phase in _TERMINAL and (
                        now - pod.created_at > self.reap_s):
                    self._reap(pod)
            for _ in range(self.size - live):
                self._create_standby(cls)

    def _pool_pods(self, cls: str, state: str) -> list[Pod]:
        return self.cluster.list_pods(
            self.namespace,
            {POOL_CLASS_LABEL: cls, POOL_STATE_LABEL: state})

    def _reap(self, pod: Pod) -> None:
        try:
            self.cluster.delete_pod(pod.namespace, pod.name)
            self.reaped += 1
        except (KubeApiError, OSError):
            pass                    # next tick retries

    def _create_standby(self, cls: str) -> None:
        import uuid

        with self._lock:
            name = f"{self.name_prefix}-{cls}-{self._seq}"
            self._seq += 1
        pod = Pod(
            name=name, namespace=self.namespace,
            labels={POOL_CLASS_LABEL: cls, POOL_STATE_LABEL: "standby"},
            # per-pod exec token (zygote.py SECURITY note): the fork
            # server refuses requests without it, and it lives in the pod
            # spec — readable exactly by principals that could claim
            # through the apiserver anyway
            env={**self.env, "KFT_ZYGOTE_TOKEN": uuid.uuid4().hex},
            command=list(self.command),
            image=self.image,
            node_selector=(
                {"cloud.google.com/gke-tpu-accelerator": f"tpu-{cls}"}
                if cls != "default" else {}),
            gang=False,     # standbys schedule the moment they exist
        )
        try:
            create_and_admit(self.cluster, pod)
            self.created += 1
        except (KubeApiError, OSError):
            pass                    # apiserver hiccup: next tick retries

    def standby_count(self, cls: Optional[str] = None) -> int:
        classes = [cls] if cls else self.classes
        return sum(
            1 for c in classes for p in self._pool_pods(c, "standby")
            if p is not None and p.phase not in _TERMINAL)

    def claimable(self, cls: Optional[str] = None) -> int:
        """RUNNING standbys in a class (or all) — what a claim can
        actually win right now. The reconciler's per-worker replacement
        decision keys on this: replacing onto a cold pod would be slower
        than the gang restart it is meant to beat. Racy by nature (a
        concurrent claim may win the pod first); the loser of that race
        cold-falls-back, counted."""
        classes = [cls] if cls else self.classes
        return sum(
            1 for c in classes for p in self._pool_pods(c, "standby")
            if p is not None and p.phase == PodPhase.RUNNING)

    def snapshot(self) -> dict:
        return {
            "claims": self.claims,
            "fallbacks": self.fallbacks,
            "dead_claims": self.dead_claims,
            "claim_errors": self.claim_errors,
            "created": self.created,
            "reaped": self.reaped,
            "prefetched_entries": self.prefetched_entries,
            "prefetch_errors": self.prefetch_errors,
            "reclaims": self.reclaims,
            "reclaim_noops": self.reclaim_noops,
            "standby": self.standby_count(),
        }

    # ------------------------------------------------------------ claim --

    def claim_and_exec(self, job_pod: Pod) -> Optional[Pod]:
        """Claim a standby pod for ``job_pod`` and start its worker.

        Per candidate: read the live manifest (zygote address + fresh
        resourceVersion), compare-and-swap the claim labels (losing the
        race 409s — move on), then deliver the worker argv/env to the
        resident zygote. A zygote that died between claim and use is
        reaped and the next candidate tried. Returns the claimed Pod, or
        None (counted fallback) when the pool is dry."""
        cls = self.pool_class_for(job_pod)
        for cand in self._pool_pods(cls, "standby"):
            if cand is None or cand.phase != PodPhase.RUNNING:
                continue
            claimed = self._try_claim(cand, job_pod)
            if claimed is not None:
                self.claims += 1
                return claimed
        self.fallbacks += 1
        return None

    def _try_claim(self, cand: Pod, job_pod: Pod) -> Optional[Pod]:
        # live manifest: the claim must key off the SERVER's view (the
        # informer cache may lag the node agent's zygote-addr annotation)
        try:
            doc = self.cluster._request(
                "GET", self.cluster._pod_path(cand.namespace, cand.name))
        except (KubeApiError, OSError):
            return None
        meta = doc.get("metadata") or {}
        ann = meta.get("annotations") or {}
        addr = ann.get(ZYGOTE_ADDR_ANNOTATION)
        if not addr or (doc.get("status") or {}).get("phase") != "Running":
            return None                   # zygote not announced yet
        if (meta.get("labels") or {}).get(POOL_STATE_LABEL) != "standby":
            return None                   # someone else already claimed it
        try:
            rv = int(meta.get("resourceVersion") or 0)
        except (TypeError, ValueError):
            return None
        patch = {"metadata": {
            "labels": {**job_pod.labels,
                       POOL_CLASS_LABEL: self.pool_class_for(job_pod),
                       POOL_STATE_LABEL: "claimed"},
            "annotations": {
                CLAIMED_AS_ANNOTATION: job_pod.name,
                # late-bound env published like any admitted pod's, so a
                # restarted controller adopting this pod reconstructs it
                **{ENV_ANNOTATION_PREFIX + k: str(v)
                   for k, v in job_pod.env.items()},
            }}}
        try:
            self.cluster.patch_pod(cand.namespace, cand.name, patch,
                                   expect_rv=rv)
        except KubeApiError as e:
            if e.code not in (404, 409):
                # 409 = lost the claim race, 404 = the reaper won it
                # (expired standby deleted between GET and PATCH) — both
                # are normal churn. Anything else is a broken control
                # plane, which must stay distinguishable from a busy pool.
                self.claim_errors += 1
            return None
        except OSError:
            self.claim_errors += 1
            return None
        # we own the pod now — start the worker in it. The exec token is
        # read from the SERVER manifest (not local state) so a restarted
        # controller adopting the pool can still claim. A reclaimed pod's
        # token was ROTATED (pod spec env is immutable) and lives in the
        # token annotation, which wins over the spec env original.
        token = ann.get(ZYGOTE_TOKEN_ANNOTATION) or next(
            (e.get("value", "") for c in (doc.get("spec") or {}).get(
                "containers", [{}])[:1]
             for e in (c.get("env") or [])
             if e.get("name") == "KFT_ZYGOTE_TOKEN"), "")
        env = self._exec_env(job_pod, cand)
        # the claimed standby pre-fetches the executable depot in the
        # BACKGROUND: started before the exec RPC so it normally beats
        # the worker to its first depot read (the worker pays fork +
        # imports + state init first), but never blocking admission on
        # entry transfer — a worker whose cache is still cold simply
        # fetches the remote itself (LocalCacheDepot writes through)
        threading.Thread(target=self._prefetch_depot, args=(env,),
                         daemon=True,
                         name=f"depot-prefetch-{cand.name}").start()
        watcher = self._exec(addr, cand, job_pod.command, env, token)
        if watcher is None:
            # claimed a corpse (zygote died between claim and use): make
            # the death visible and let reconcile replenish; the caller
            # moves on to the next candidate / cold fallback
            self.dead_claims += 1
            try:
                self.cluster.set_phase(
                    cand.namespace, cand.name, PodPhase.FAILED, -1)
            except (KubeApiError, OSError):
                pass
            self._reap(cand)
            return None
        # the watcher thread owns its own lifetime (daemon thread holding
        # the claim connection); the registry exists only so reclaim()
        # can disarm the exit report before killing the worker
        self._watchers[(cand.namespace, cand.name)] = watcher
        # fold the new identity into the local object too (the patch_pod
        # fold already synced labels; env is local-only state)
        cand.labels.update(patch["metadata"]["labels"])
        cand.env.update(env)
        cand.scheduled = True
        return cand

    def _prefetch_depot(self, env: dict, limit: int = 8) -> None:
        """Sync the newest executable-depot entries into the pod-local
        cache named by the worker env (KFT_DEPOT_CACHE). In this
        single-binary architecture the controller performs the fetch (the
        cache dir is host-shared, like the kubelet's announce file); on a
        real cluster the standby pod's node agent would run the same sync
        against its own disk. Runs on a daemon thread off the claim path
        (entries can be large), best-effort and counted — a depot that
        cannot be synced costs the claim nothing but the fast path."""
        if not env.get("KFT_DEPOT") or not env.get("KFT_DEPOT_CACHE"):
            return
        try:
            from kubeflow_tpu.parallel.depot import depot_from_env

            depot = depot_from_env(env)     # LocalCacheDepot: get() =
            for key in depot.keys()[:limit]:  # write-through to the cache
                if depot.cache.get(key) is None \
                        and depot.get(key) is not None:
                    self.prefetched_entries += 1
        except Exception:
            self.prefetch_errors += 1

    def _exec_env(self, job_pod: Pod, cand: Pod) -> dict:
        """The worker env, with heartbeat/phase URLs re-pointed at the
        pod identity that ACTUALLY runs the worker: sweeps iterate live
        pods by name, so beats must arrive under the claimed pod's name,
        not the cold twin's."""
        frag_old = f"/pods/{job_pod.name}/"
        frag_new = f"/pods/{cand.name}/"
        return {k: (v.replace(frag_old, frag_new)
                    if isinstance(v, str) else v)
                for k, v in job_pod.env.items()}

    def _exec(self, addr: str, cand: Pod, argv: list[str],
              env: dict, token: str = "") -> Optional[_ClaimWatcher]:
        host, _, port = addr.rpartition(":")
        try:
            conn = socket.create_connection(
                (host, int(port)), timeout=self.dial_timeout_s)
        except (OSError, ValueError):
            return None
        try:
            # no "log": the forked worker inherits the zygote's
            # stdout/stderr — the pod log
            conn.sendall(json.dumps(
                {"argv": argv, "env": env, "token": token}
            ).encode() + b"\n")
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("zygote hung up")
                buf += chunk
            line, rest = buf.split(b"\n", 1)
            int(json.loads(line)["pid"])      # fork acknowledged
        except (OSError, ValueError, KeyError):
            try:
                conn.close()
            except OSError:
                pass
            return None
        conn.settimeout(None)       # the exit read blocks for the pod life
        watcher = _ClaimWatcher(self.cluster, cand.namespace, cand.name,
                                conn, pending=rest)
        watcher.start()
        return watcher

    # ---------------------------------------------------------- reclaim --

    def reclaim(self, namespace: str, pod_name: str) -> bool:
        """Return a CLAIMED pod to the pool as a claimable standby — the
        early-stop arc: claimed → running → reclaimed → claimable.

        Order matters: (1) disarm the exit watcher, so the kill below is
        not reported as a terminal pod phase (terminal-wins would wedge
        the pod un-claimable forever); if the worker already finished and
        reported, completion won the race — counted no-op, exactly one
        terminal outcome. (2) Ask the resident zygote to SIGKILL the
        worker's process group and ROTATE its exec token, fencing out any
        late exec from the stopped trial. (3) CAS-patch the pod back to
        pool-only labels (job labels nulled out, so the job's selector —
        and its pod cleanup — can never touch the returned pod) with the
        fresh token as an annotation. (4) Drop the job-pod-name alias.

        Every failure path is a counted no-op (``reclaim_noops``), never
        a crash: a dead zygote is marked FAILED and reaped (replenish
        covers it), a lost CAS means someone else moved the pod first."""
        import uuid

        key = (namespace, pod_name)
        try:
            doc = self.cluster._request(
                "GET", self.cluster._pod_path(namespace, pod_name))
        except (KubeApiError, OSError):
            self.reclaim_noops += 1         # already deleted/apiserver gone
            return False
        meta = doc.get("metadata") or {}
        ann = meta.get("annotations") or {}
        labels = meta.get("labels") or {}
        addr = ann.get(ZYGOTE_ADDR_ANNOTATION)
        if (labels.get(POOL_STATE_LABEL) != "claimed" or not addr
                or (doc.get("status") or {}).get("phase") != "Running"):
            # not ours to return: a cold-fallback pod (no pool labels), a
            # pod that already went terminal, or a double reclaim. The
            # watcher (if any) stays armed — a still-running worker's
            # eventual exit must keep reporting.
            self.reclaim_noops += 1
            return False
        # validated against the live manifest — NOW take the exit report
        # out of play. disarm() losing means the worker finished between
        # the GET and here: completion won, its terminal report stands
        # (our stale-rv CAS below could not have landed anyway).
        watcher = self._watchers.get(key)
        if watcher is not None and not watcher.disarm():
            self._watchers.pop(key, None)
            self.reclaim_noops += 1
            return False
        old_token = ann.get(ZYGOTE_TOKEN_ANNOTATION) or next(
            (e.get("value", "") for c in (doc.get("spec") or {}).get(
                "containers", [{}])[:1]
             for e in (c.get("env") or [])
             if e.get("name") == "KFT_ZYGOTE_TOKEN"), "")
        new_token = uuid.uuid4().hex
        if not self._reclaim_rpc(addr, old_token, new_token):
            # dead zygote: the pod cannot serve another claim — make the
            # death visible and let reconcile replenish
            self.reclaim_noops += 1
            try:
                self.cluster.set_phase(
                    namespace, pod_name, PodPhase.FAILED, -1)
            except (KubeApiError, OSError):
                pass
            pod = self.cluster.get_pod(namespace, pod_name)
            if pod is not None:
                self._reap(pod)
            self._watchers.pop(key, None)
            return False
        try:
            rv = int(meta.get("resourceVersion") or 0)
        except (TypeError, ValueError):
            rv = None
        cls = labels.get(POOL_CLASS_LABEL, "default")
        patch = {"metadata": {
            # null out every claimed-on label (job-name/job-uid/replica-*/
            # experiment/...) so the trial job's selector no longer
            # matches; keep only the pool identity, back in standby
            "labels": {**{k: None for k in labels
                          if k not in (POOL_CLASS_LABEL, POOL_STATE_LABEL)},
                       POOL_CLASS_LABEL: cls,
                       POOL_STATE_LABEL: "standby"},
            "annotations": {
                CLAIMED_AS_ANNOTATION: None,
                ZYGOTE_TOKEN_ANNOTATION: new_token,
                # the stopped trial's late-bound env must not leak into
                # the next claimant's reconstruction
                **{k: None for k in ann
                   if k.startswith(ENV_ANNOTATION_PREFIX)},
            }}}
        try:
            self.cluster.patch_pod(namespace, pod_name, patch,
                                   expect_rv=rv)
        except (KubeApiError, OSError):
            # lost the CAS (reaper/concurrent mutation bumped rv) AFTER
            # the worker was killed and the token rotated: the pod can
            # neither serve its old claim nor be proven standby — fail it
            # so reconcile reaps and replenishes, counted no-op
            self.reclaim_noops += 1
            try:
                self.cluster.set_phase(
                    namespace, pod_name, PodPhase.FAILED, -1)
            except (KubeApiError, OSError):
                pass
            self._watchers.pop(key, None)
            return False
        release = getattr(self.cluster, "release_claim", None)
        if release is not None:
            release(namespace, pod_name)
        self._watchers.pop(key, None)
        self.reclaims += 1
        return True

    def _reclaim_rpc(self, addr: str, old_token: str,
                     new_token: str) -> bool:
        """Kill-and-rotate request to the resident zygote. False = the
        zygote is unreachable or refused (dead pod, wrong token)."""
        host, _, port = addr.rpartition(":")
        try:
            conn = socket.create_connection(
                (host, int(port)), timeout=self.dial_timeout_s)
        except (OSError, ValueError):
            return False
        try:
            conn.sendall(json.dumps(
                {"reclaim": True, "token": old_token,
                 "new_token": new_token}).encode() + b"\n")
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    return False
                buf += chunk
            return bool(json.loads(buf.split(b"\n", 1)[0]).get("reclaimed"))
        except (OSError, ValueError):
            return False
        finally:
            try:
                conn.close()
            except OSError:
                pass
