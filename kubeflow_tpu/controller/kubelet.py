"""Image-less kubelet for the Kube backend: pods become real processes.

The fake apiserver is envtest — "pods are created but never run". That is
right for reconcile-logic tests, but the warm-pool subsystem's whole claim
is a WALL-CLOCK one (submit→first-step with imports already paid), so the
kube e2e needs a node agent that actually runs pod commands. FakeKubelet
is that agent: a polling loop over the apiserver that

- spawns every scheduled (gate-lifted), Pending pod's command as a local
  subprocess — manifest env + late-bound annotation env merged over the
  host env, stdout/stderr to a per-pod log (what a container runtime
  does, minus the image);
- reports status THROUGH the apiserver: Running after spawn, terminal
  phase + exitCode when the process exits — exactly the kubelet's
  containerStatuses contract the controllers already consume;
- plays the node half of the zygote-announce contract: every pod gets
  ``KFT_ZYGOTE_ANNOUNCE`` pointing at a per-pod file; a standby zygote
  (rendezvous/zygote.py tcp form) writes its bound address there, and the
  kubelet publishes it as the ``zygote-addr`` pod annotation the
  WarmPoolController dials (on a real cluster this is pod IP + the fixed
  containerPort — the announce file is the image-less stand-in);
- kills local processes whose pods vanished server-side (pool reap, job
  teardown).

This makes ``bench.py --cluster kube`` and the warm-pool e2e honest:
the cold number pays a real interpreter + ``import jax``; the warm-claim
number forks from a genuinely pre-imported zygote pod.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Optional

from kubeflow_tpu.controller.cluster import PodPhase
from kubeflow_tpu.controller.kube import (
    ENV_ANNOTATION_PREFIX, KubeApiError, KubeCluster,
    RESTART_EPOCH_ANNOTATION,
)
from kubeflow_tpu.controller.warmpool import ZYGOTE_ADDR_ANNOTATION


class FakeKubelet:
    """``start()`` begins the sync loop; ``stop()`` reaps everything."""

    def __init__(self, apiserver_url: str, log_dir: str,
                 node: str = "kubelet-0", poll_s: float = 0.05):
        self.kube = KubeCluster(apiserver_url)
        self.log_dir = log_dir
        self.node = node
        self.poll_s = poll_s
        self.procs: dict[tuple[str, str], subprocess.Popen] = {}
        self._announced: set[tuple[str, str]] = set()
        self._reported: set[tuple[str, str]] = set()    # terminal reported
        self._starting: set[tuple[str, str]] = set()    # init step running
        self._spawned_at: dict[tuple[str, str], float] = {}
        # restart-epoch each pod's CURRENT process was spawned under; a
        # newer annotation = the operator's re-rendezvous signal -> bounce
        self._restart_epochs: dict[tuple[str, str], str] = {}
        self.restarts = 0               # in-place process restarts served
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(log_dir, exist_ok=True)

    # ---------------------------------------------------------- lifecycle --

    def start(self) -> "FakeKubelet":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"fake-kubelet-{self.node}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        for proc in self.procs.values():
            self._kill(proc)
        self.procs.clear()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.sync()
            except Exception:
                pass                    # apiserver hiccup: next tick

    # --------------------------------------------------------------- sync --

    def sync(self) -> None:
        """One kubelet pass: spawn newly scheduled pods, publish zygote
        announces, report exits, reap processes of deleted pods."""
        pods = self.kube.list_pods("", {})
        server = {(p.namespace, p.name) for p in pods if p is not None}
        for pod in pods:
            if pod is None:
                continue
            key = (pod.namespace, pod.name)
            if (key in self.procs and pod.scheduled
                    and pod.phase == PodPhase.PENDING
                    and self.procs[key].poll() is None
                    # grace window: an async-init spawn finishing between
                    # the list snapshot and this iteration also reads
                    # (live proc, snapshot-Pending) — a genuine dead
                    # incarnation stays Pending far longer than the
                    # spawn->Running report takes
                    and time.time() - self._spawned_at.get(key, 0) > 2.0):
                # a Pending pod backed by a live local process is a NEW
                # incarnation of the name whose delete+recreate fell
                # between two polls: the process belongs to the dead
                # incarnation — kill it or the new pod wedges Pending
                # forever behind the zombie's key
                self._kill(self.procs.pop(key))
                self._announced.discard(key)
                self._reported.discard(key)
            if (key not in self.procs and key not in self._starting
                    and pod.scheduled
                    and pod.phase == PodPhase.PENDING and pod.command):
                # a Pending pod we already reported terminal is a NEW
                # incarnation of the name (gang restart deletes+recreates)
                self._reported.discard(key)
                self._announced.discard(key)
                self._spawn(pod)
            self._maybe_restart_in_place(pod, key)
            self._publish_announce(key)
            self._report_exit(key)
        for key in [k for k in list(self.procs) if k not in server]:
            self._kill(self.procs.pop(key))
            self._announced.discard(key)
            self._reported.discard(key)
            self._restart_epochs.pop(key, None)
        # _starting keys clear themselves when their init thread finishes;
        # a deleted pod's late spawn is reaped by the loop above next pass

    def _maybe_restart_in_place(self, pod, key: tuple[str, str]) -> None:
        """The operator's re-rendezvous signal (elastic recovery): a
        bumped restart-epoch annotation on a pod with a live process means
        'kill and respawn the process, keep the pod' — the survivor's half
        of per-worker replacement. Env updates ride as annotations and win
        over the creation-time env."""
        epoch = (pod.annotations or {}).get(RESTART_EPOCH_ANNOTATION)
        if epoch is None:
            return
        proc = self.procs.get(key)
        if proc is None or proc.poll() is not None:
            # no live process to bounce: record the epoch so a later spawn
            # doesn't immediately re-restart itself
            self._restart_epochs[key] = epoch
            return
        if self._restart_epochs.get(key) == epoch:
            return
        self._restart_epochs[key] = epoch
        self.procs.pop(key, None)       # off the exit reporter FIRST: this
        self._kill(proc)                # death is ours, not a pod failure
        self._reported.discard(key)
        self.restarts += 1
        with open(self._log_path(key), "ab") as log:
            log.write(f"kubelet: in-place restart (epoch {epoch})\n"
                      .encode())
        self._spawn(pod)

    def _spawn(self, pod) -> None:
        key = (pod.namespace, pod.name)
        env = dict(os.environ)
        env.update({k: str(v) for k, v in pod.env.items()})
        # late-bound annotation env (merged AFTER pod.env: an updated
        # annotation — e.g. the new rendezvous epoch — must win over the
        # creation-time value baked into the manifest env fold)
        for k, v in (pod.annotations or {}).items():
            if k.startswith(ENV_ANNOTATION_PREFIX):
                env[k[len(ENV_ANNOTATION_PREFIX):]] = str(v)
        if RESTART_EPOCH_ANNOTATION in (pod.annotations or {}):
            self._restart_epochs[key] = pod.annotations[
                RESTART_EPOCH_ANNOTATION]
        env["KFT_ZYGOTE_ANNOUNCE"] = self._announce_path(key)
        try:
            # a recreated pod must not inherit its predecessor's address
            os.unlink(self._announce_path(key))
        except FileNotFoundError:
            pass
        if pod.init_command:
            # initContainer contract (the storage-initializer role): runs
            # to completion before the main command, OFF the sync loop —
            # a slow storage download must not freeze every other pod's
            # spawn/announce/exit reporting (the local backend runs the
            # same contract async for the same reason)
            self._starting.add(key)
            threading.Thread(target=self._init_then_spawn,
                             args=(pod, key, env), daemon=True,
                             name=f"kubelet-init-{pod.name}").start()
            return
        self._main_spawn(pod, key, env)

    def _init_then_spawn(self, pod, key, env) -> None:
        try:
            with open(self._log_path(key), "ab") as log:
                try:
                    rc = subprocess.run(
                        pod.init_command, env=env, stdout=log,
                        stderr=subprocess.STDOUT, timeout=300).returncode
                except (OSError, subprocess.TimeoutExpired) as e:
                    log.write(f"kubelet init failed: {e}\n".encode())
                    rc = -1
                if rc != 0:
                    log.write(
                        f"kubelet: init command exited {rc}\n".encode())
                    self._set_phase(key, PodPhase.FAILED, rc)
                    self._reported.add(key)
                    return
            self._main_spawn(pod, key, env)
        finally:
            self._starting.discard(key)

    def _main_spawn(self, pod, key, env) -> None:
        log = open(self._log_path(key), "ab")
        try:
            proc = subprocess.Popen(
                pod.command, env=env, stdout=log, stderr=subprocess.STDOUT)
        except OSError as e:
            log.write(f"kubelet spawn failed: {e}\n".encode())
            log.close()
            self._set_phase(key, PodPhase.FAILED, -1)
            self._reported.add(key)
            return
        log.close()                     # the child owns its copy of the fd
        self._spawned_at[key] = time.time()
        self.procs[key] = proc
        self._set_phase(key, PodPhase.RUNNING)

    def _publish_announce(self, key: tuple[str, str]) -> None:
        if key in self._announced or key not in self.procs:
            return
        path = self._announce_path(key)
        try:
            with open(path) as f:
                addr = f.read().strip()
        except OSError:
            return                      # zygote (if any) not bound yet
        if not addr:
            return
        # image-less substitution: the zygote bound 0.0.0.0/ephemeral on
        # THIS host; pod-network address = loopback + that port
        port = addr.rsplit(":", 1)[-1]
        try:
            self.kube.patch_pod(key[0], key[1], {"metadata": {
                "annotations": {
                    ZYGOTE_ADDR_ANNOTATION: f"127.0.0.1:{port}"}}})
        except (KubeApiError, OSError):
            return
        self._announced.add(key)

    def _report_exit(self, key: tuple[str, str]) -> None:
        proc = self.procs.get(key)
        if proc is None or key in self._reported:
            return
        rc = proc.poll()
        if rc is None:
            return
        self._reported.add(key)
        self.procs.pop(key, None)
        self._set_phase(
            key, PodPhase.SUCCEEDED if rc == 0 else PodPhase.FAILED, rc)

    # ------------------------------------------------------------ helpers --

    def _set_phase(self, key, phase, exit_code=None) -> None:
        try:
            self.kube.set_phase(key[0], key[1], phase, exit_code)
        except (KubeApiError, OSError):
            pass        # pod deleted mid-report / apiserver gone

    def _log_path(self, key) -> str:
        return os.path.join(self.log_dir, f"{key[0]}-{key[1]}.log")

    def _announce_path(self, key) -> str:
        return os.path.join(self.log_dir, f"{key[0]}-{key[1]}.zygote-addr")

    def pod_log(self, namespace: str, name: str) -> str:
        path = self._log_path((namespace, name))
        try:
            with open(path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    @staticmethod
    def _kill(proc: subprocess.Popen) -> None:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def zygote_pid(self, namespace: str, name: str) -> Optional[int]:
        """Test hook: the local pid backing a pod (e.g. to kill a zygote
        between claim and use)."""
        proc = self.procs.get((namespace, name))
        return proc.pid if proc is not None else None

    def wait_announced(self, namespace: str, name: str,
                       timeout_s: float = 60.0) -> bool:
        """Block until a pod's zygote address annotation is published —
        the 'pool is warm' barrier benches use so the zygote's one-time
        import cost lands outside the measured window."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if (namespace, name) in self._announced:
                return True
            time.sleep(0.05)
        return False
