"""HPO layer — Katib-equivalent hyperparameter optimization (SURVEY.md §2.3)."""

from kubeflow_tpu.hpo.client import TuneClient, tune
from kubeflow_tpu.hpo.controller import (
    CallableTrialRunner, ExperimentController, JobTrialRunner,
)
from kubeflow_tpu.hpo.earlystopping import ASHA, MedianStop, make_stopper
from kubeflow_tpu.hpo.search import ALGORITHMS, make_algorithm
from kubeflow_tpu.hpo.service import (
    SuggestionClient, SuggestionCore, SuggestionServer,
)
from kubeflow_tpu.hpo.types import (
    AlgorithmSpec, EarlyStoppingSpec, Experiment, ObjectiveGoalType,
    ObjectiveSpec, ParameterSpec, ParameterType, ResumePolicy, Trial,
    TrialState,
)

__all__ = [
    "ALGORITHMS", "ASHA", "AlgorithmSpec", "CallableTrialRunner",
    "EarlyStoppingSpec", "Experiment", "ExperimentController",
    "JobTrialRunner", "MedianStop", "ObjectiveGoalType", "ObjectiveSpec",
    "ParameterSpec", "ParameterType", "ResumePolicy", "SuggestionClient",
    "SuggestionCore", "SuggestionServer", "Trial", "TrialState", "TuneClient",
    "make_algorithm", "make_stopper", "tune",
]
