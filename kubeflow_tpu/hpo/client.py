"""TuneClient — the KatibClient-equivalent SDK (SURVEY.md §2.3: `tune()`
objective-fn-to-Experiment sugar, create_experiment, get results)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from kubeflow_tpu.hpo.controller import (
    CallableTrialRunner, ExperimentController, JobTrialRunner, TrialRunner,
)
from kubeflow_tpu.hpo.types import (
    AlgorithmSpec, EarlyStoppingSpec, Experiment, ObjectiveGoalType,
    ObjectiveSpec, ParameterSpec, Trial,
)


def tune(
    objective_fn: Callable,
    parameters: Sequence[ParameterSpec],
    *,
    metric_name: str = "objective",
    goal_type: str = "minimize",
    goal: Optional[float] = None,
    algorithm: str = "random",
    algorithm_settings: Optional[dict] = None,
    early_stopping: Optional[EarlyStoppingSpec] = None,
    max_trial_count: int = 12,
    parallel_trial_count: int = 3,
    name: str = "tune",
    timeout: float = 300.0,
) -> Experiment:
    """Run HPO over a local objective ``fn(params, report) -> float``.

    The sugar path: builds the Experiment, runs trials as local callables,
    returns the finished experiment (``.best_trial`` for the winner).
    """
    exp = Experiment(
        name=name,
        parameters=list(parameters),
        objective=ObjectiveSpec(
            metric_name=metric_name,
            goal_type=ObjectiveGoalType(goal_type),
            goal=goal,
        ),
        algorithm=AlgorithmSpec(name=algorithm,
                                settings=algorithm_settings or {}),
        early_stopping=early_stopping,
        max_trial_count=max_trial_count,
        parallel_trial_count=parallel_trial_count,
    )
    runner = CallableTrialRunner(objective_fn,
                                 max_workers=parallel_trial_count)
    try:
        return ExperimentController(exp, runner).run(timeout=timeout)
    finally:
        runner.shutdown()


class TuneClient:
    """Experiment lifecycle over a TrialRunner (production: JobTrialRunner
    over the job controller; tests: CallableTrialRunner)."""

    def __init__(self, runner: TrialRunner):
        self.runner = runner
        self._controllers: dict[str, ExperimentController] = {}

    def create_experiment(self, exp: Experiment) -> ExperimentController:
        if exp.name in self._controllers:
            raise KeyError(f"experiment {exp.name} already exists")
        ctl = ExperimentController(exp, self.runner)
        self._controllers[exp.name] = ctl
        return ctl

    def get_experiment(self, name: str) -> Optional[Experiment]:
        ctl = self._controllers.get(name)
        return ctl.exp if ctl else None

    def wait_for_experiment(self, name: str, timeout: float = 600.0) -> Experiment:
        return self._controllers[name].run(timeout=timeout)

    def get_optimal_hyperparameters(self, name: str) -> Optional[Trial]:
        exp = self.get_experiment(name)
        return exp.best_trial if exp else None
