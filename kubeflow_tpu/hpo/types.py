"""HPO API types — the Experiment/Suggestion/Trial surface.

Capability parity with the reference's Katib CRDs (SURVEY.md §2.3:
Experiment/Suggestion/Trial with parallelism, objective goal, max trial
counts, early stopping; NAS via ``algorithm.name="enas"`` and the DARTS
one-shot searcher in ``hpo.nas``), redesigned for the TPU stack:

- Trials are JAXJobs (or local callables in tests) — the trial template is a
  JobSpec factory with ``${param}`` substitution, mirroring Katib's
  trialTemplate parameter substitution.
- Metrics flow through the native metrics path (training.MetricsWriter JSONL
  → observation log), NOT a stdout-scraping sidecar (SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
import enum
import math
import time
from typing import Any, Optional


class ParameterType(str, enum.Enum):
    DOUBLE = "double"
    INT = "int"
    CATEGORICAL = "categorical"
    DISCRETE = "discrete"       # ordered numeric choices


@dataclasses.dataclass
class ParameterSpec:
    """Search-space dimension (Katib's feasibleSpace equivalent)."""

    name: str
    type: ParameterType = ParameterType.DOUBLE
    min: Optional[float] = None
    max: Optional[float] = None
    step: Optional[float] = None
    values: list[Any] = dataclasses.field(default_factory=list)
    log: bool = False           # sample/model in log10 space

    def validate(self) -> None:
        if self.type in (ParameterType.DOUBLE, ParameterType.INT):
            if self.min is None or self.max is None or self.min >= self.max:
                raise ValueError(f"{self.name}: need min < max")
            if self.log and self.min <= 0:
                raise ValueError(f"{self.name}: log scale needs min > 0")
        else:
            if not self.values:
                raise ValueError(f"{self.name}: need values")

    # -- unit-cube mapping used by every numeric algorithm ------------------
    def to_unit(self, value: Any) -> float:
        if self.type == ParameterType.CATEGORICAL:
            return self.values.index(value) / max(1, len(self.values) - 1)
        if self.type == ParameterType.DISCRETE:
            return self.values.index(value) / max(1, len(self.values) - 1)
        lo, hi = float(self.min), float(self.max)
        v = float(value)
        if self.log:
            lo, hi, v = math.log10(lo), math.log10(hi), math.log10(v)
        return (v - lo) / (hi - lo)

    def from_unit(self, u: float) -> Any:
        u = min(1.0, max(0.0, u))
        if self.type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            idx = min(len(self.values) - 1, int(u * len(self.values)))
            return self.values[idx]
        lo, hi = float(self.min), float(self.max)
        if self.log:
            lo, hi = math.log10(lo), math.log10(hi)
        v = lo + u * (hi - lo)
        if self.log:
            v = 10.0 ** v
        if self.type == ParameterType.INT:
            v = int(round(v))
            if self.step:
                v = int(self.min + round((v - self.min) / self.step) * self.step)
            return max(int(self.min), min(int(self.max), v))
        if self.step:
            v = self.min + round((v - self.min) / self.step) * self.step
        return max(float(self.min), min(float(self.max), float(v)))

    def grid(self, n: int) -> list[Any]:
        if self.type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            return list(self.values)
        if self.type == ParameterType.INT and (self.max - self.min) < n:
            return list(range(int(self.min), int(self.max) + 1))
        return [self.from_unit(i / max(1, n - 1)) for i in range(n)]


class ObjectiveGoalType(str, enum.Enum):
    MINIMIZE = "minimize"
    MAXIMIZE = "maximize"


@dataclasses.dataclass
class ObjectiveSpec:
    metric_name: str = "loss"
    goal_type: ObjectiveGoalType = ObjectiveGoalType.MINIMIZE
    goal: Optional[float] = None            # stop when reached
    additional_metrics: list[str] = dataclasses.field(default_factory=list)

    def better(self, a: float, b: float) -> bool:
        """True if a is strictly better than b."""
        if self.goal_type == ObjectiveGoalType.MINIMIZE:
            return a < b
        return a > b

    def reached(self, value: float) -> bool:
        if self.goal is None:
            return False
        if self.goal_type == ObjectiveGoalType.MINIMIZE:
            return value <= self.goal
        return value >= self.goal


@dataclasses.dataclass
class AlgorithmSpec:
    name: str = "random"     # random|grid|sobol|tpe|cmaes|hyperband
    settings: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class EarlyStoppingSpec:
    name: str = "medianstop"     # medianstop|asha|none
    settings: dict[str, Any] = dataclasses.field(default_factory=dict)
    min_trials_required: int = 3
    start_step: int = 1


class TrialState(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    EARLY_STOPPED = "EarlyStopped"
    KILLED = "Killed"


@dataclasses.dataclass
class Observation:
    """One reported metric point — Katib's ObservationLog row."""

    metric_name: str
    value: float
    step: int = 0
    timestamp: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass
class Trial:
    name: str
    parameters: dict[str, Any]
    state: TrialState = TrialState.CREATED
    observations: list[Observation] = dataclasses.field(default_factory=list)
    objective_value: Optional[float] = None
    start_time: float = dataclasses.field(default_factory=time.time)
    completion_time: Optional[float] = None

    def intermediate(self, metric: str) -> list[tuple[int, float]]:
        return [(o.step, o.value) for o in self.observations
                if o.metric_name == metric]

    def is_finished(self) -> bool:
        return self.state in (TrialState.SUCCEEDED, TrialState.FAILED,
                              TrialState.EARLY_STOPPED, TrialState.KILLED)


class ResumePolicy(str, enum.Enum):
    NEVER = "Never"
    LONG_RUNNING = "LongRunning"
    FROM_VOLUME = "FromVolume"


@dataclasses.dataclass
class Experiment:
    name: str
    parameters: list[ParameterSpec]
    objective: ObjectiveSpec = dataclasses.field(default_factory=ObjectiveSpec)
    algorithm: AlgorithmSpec = dataclasses.field(default_factory=AlgorithmSpec)
    early_stopping: Optional[EarlyStoppingSpec] = None
    parallel_trial_count: int = 3
    max_trial_count: int = 12
    max_failed_trial_count: int = 3
    resume_policy: ResumePolicy = ResumePolicy.NEVER
    namespace: str = "default"

    # status
    trials: list[Trial] = dataclasses.field(default_factory=list)
    succeeded: bool = False
    failed: bool = False
    completion_reason: str = ""

    def validate(self) -> None:
        if not self.parameters:
            raise ValueError("experiment has no parameters")
        for p in self.parameters:
            p.validate()
        if self.parallel_trial_count < 1:
            raise ValueError("parallel_trial_count must be >= 1")

    @property
    def best_trial(self) -> Optional[Trial]:
        best = None
        for t in self.trials:
            if t.state != TrialState.SUCCEEDED or t.objective_value is None:
                continue
            if best is None or self.objective.better(
                t.objective_value, best.objective_value
            ):
                best = t
        return best

    def counts(self) -> dict[TrialState, int]:
        out = {s: 0 for s in TrialState}
        for t in self.trials:
            out[t.state] += 1
        return out
