"""Trial swarm: pack many concurrent HPO trials onto the warm pool.

The Podracer argument (PAPERS.md: Podracer architectures; TPU concurrency
studies) is that accelerator utilization at small-job scale comes from
MULTIPLEXING work onto warm hardware, not from one big job. The seeded
HPO triangle predates every warm-start lever built since — each trial
paid cold pod spawn + a full compile. This module is the missing
execution layer that composes them:

- **Warm claims**: trials submit through the normal job layer, whose
  admission claims a pre-warmed standby (controller/warmpool.py) — trial
  submit→first-step is fork + state init + a depot read, not interpreter
  + imports + compile. A dry pool cold-falls-back, counted as
  ``pool_starvation`` (the replenish-rate signal rides the pool's own
  ``created`` counter).
- **Shared compile**: scalar hyperparameters (lr, weight decay, ...) are
  TRACED runtime arguments of the trial program (hpo/trial_worker.py),
  so every trial of a structural config lowers to identical HLO and
  shares ONE depot entry (``fingerprint(stage="hpo-trial")``). The
  runner designates the first trial per structural config as the depot
  publisher; every later one is a follower (``KFT_DEPOT_WAIT_S``) that
  waits for the publish instead of racing it — deterministic
  one-publish/N−1-hits instead of a thundering first batch.
- **Early-stop reclaim**: when MedianStop/ASHA kills a trial, its pod is
  RETURNED to the pool as a claimable zygote-warm standby
  (``WarmPoolController.reclaim``: kill worker, rotate exec token,
  un-label) instead of deleted — the pool self-replenishes under churn.
  The job record is forgotten FIRST (``JobController.forget``) so no
  reconcile pass mistakes the returning pod for a dead worker.
- **Per-trial spans**: ``trial.claim`` / ``trial.stopped`` posted by the
  runner and ``trial.load`` / ``trial.step`` by the worker, all through
  the PR 10 heartbeat span path, folded into the operator job trace;
  ``experiment_trace`` merges every trial's trace into one
  Perfetto-loadable export.

Counters surface as operator metrics (``kft_swarm_*``, rendered through
obs/expo) and in ``snapshot()`` for bench JSON.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from kubeflow_tpu.controller.reconciler import JobController, _job_selector
from kubeflow_tpu.hpo.controller import JobTrialRunner
from kubeflow_tpu.hpo.types import TrialState
from kubeflow_tpu.obs.histogram import Histogram


class SwarmTrialRunner(JobTrialRunner):
    """JobTrialRunner that runs an Experiment as a warm-pool swarm.

    ``pool`` is the WarmPoolController admission claims from (it must
    also be attached as ``cluster.warm_pool``); ``operator`` (optional)
    receives trial spans over its heartbeat path and the ``kft_swarm_*``
    metrics; ``structural_keys`` names the hyperparameters that CHANGE
    THE PROGRAM (width/depth — they legitimately fork the depot key);
    everything else is assumed scalar and compile-shared.
    """

    def __init__(self, jobs: JobController, template: Callable,
                 metrics_dir: str, *, pool, operator=None,
                 structural_keys=(), follower_wait_s: float = 30.0):
        super().__init__(jobs, template, metrics_dir)
        self.pool = pool
        self.operator = operator
        self.structural_keys = tuple(structural_keys)
        self.follower_wait_s = float(follower_wait_s)
        self._lock = threading.Lock()
        # structural configs that already have a designated depot
        # publisher — the first trial of each config compiles+publishes,
        # all later ones follower-wait for that entry
        self._publishers: set[tuple] = set()
        # per-trial records for bench/trace: claim timing, warm/cold,
        # pod identity, stashed phases+trace for killed trials
        self.records: dict[str, dict] = {}
        self.claim_hist = Histogram()
        # counters (monotonic; exported as kft_swarm_*_total)
        self.trials_running = 0      # trials that entered RUNNING
        self.trials_succeeded = 0
        self.trials_failed = 0
        self.trials_stopped = 0      # early-stopped / killed
        self.warm_claims = 0
        self.pool_starvation = 0     # trials that cold-fell-back
        self.reclaims = 0            # pods returned to the pool
        self.reclaim_noops = 0

    # ------------------------------------------------------------ start --

    def structural_of(self, params: dict) -> tuple:
        return tuple(
            (k, str(params.get(k))) for k in self.structural_keys)

    def _prepare_job(self, job, trial, experiment) -> None:
        structural = self.structural_of(trial.parameters)
        with self._lock:
            follower = structural in self._publishers
            self._publishers.add(structural)
        rec = self.records.setdefault(trial.name, {})
        rec["structural"] = structural
        rec["follower"] = follower
        if follower:
            # follower-wait for the designated publisher's depot entry
            # instead of racing it with an identical compile; a dead
            # transport or timeout ends the wait and compiles locally,
            # counted (parallel/depot.py load_or_compile semantics)
            for spec in job.replica_specs.values():
                spec.template.env.setdefault(
                    "KFT_DEPOT_WAIT_S", str(self.follower_wait_s))

    def start(self, trial, experiment):
        t0 = time.time()
        super().start(trial, experiment)
        rec = self.records.setdefault(trial.name, {})
        rec["t_submit"] = t0
        if trial.state != TrialState.RUNNING:
            # admission rejected: the publisher designation must not pin
            # this structural config on a trial that never ran
            with self._lock:
                if not rec.get("follower"):
                    self._publishers.discard(rec.get("structural", ()))
            self.trials_failed += 1
            return
        dt = time.time() - t0
        ns = experiment.namespace
        job = self.jobs.get(ns, trial.name)
        # resolve where the trial actually runs: a warm claim aliases the
        # job pod name to the claimed standby
        claims = getattr(self.jobs.cluster, "_claims", {})
        pods = (self.jobs.cluster.list_pods(ns, _job_selector(job))
                if job is not None else [])
        claimed = [p.name for p in pods
                   if (p.namespace, p.name) in set(claims.values())]
        warm = bool(claimed)
        rec.update(claim_s=dt, warm=warm,
                   pod=(claimed[0] if claimed
                        else (pods[0].name if pods else "")))
        self.trials_running += 1
        self.claim_hist.observe(dt)
        if warm:
            self.warm_claims += 1
        else:
            self.pool_starvation += 1
        self._metric("inc", "kft_swarm_trials_running_total", experiment)
        if not warm:
            self._metric("inc", "kft_swarm_pool_starvation_total",
                         experiment)
        self._metric("observe", "kft_swarm_claim_seconds", experiment, dt)
        self._post_spans(ns, trial.name, rec.get("pod") or trial.name, [{
            "name": "trial.claim", "t0": t0, "t1": t0 + dt,
            "attrs": {"trial": trial.name, "warm": int(warm),
                      "pod": rec.get("pod", "")}}])

    # ------------------------------------------------------------- poll --

    def poll(self, trial, experiment):
        prev = trial.state
        super().poll(trial, experiment)
        if prev == TrialState.RUNNING and trial.is_finished():
            if trial.state == TrialState.SUCCEEDED:
                self.trials_succeeded += 1
                self._metric("inc", "kft_swarm_trials_succeeded_total",
                             experiment)
            else:
                self.trials_failed += 1
            self._stash(trial, experiment)
            self._release(trial, experiment)

    def _release(self, trial, experiment) -> None:
        """Finished trial: drop the job record so its gang reservation is
        freed (forget -> remove_group -> slice release) and delete the
        exited pods. kill() already releases through forget; without this
        twin on the success/failure path every completed trial parks its
        slice forever, and a swarm larger than the slice pool starves at
        admission once the pool is exhausted. Terminal pods cannot be
        reclaimed (reclaim requires phase=Running), so they are deleted —
        deletion also drops their job-pod-name claim aliases, and the
        pool replenishes standbys on its own clock."""
        ns = experiment.namespace
        job = self.jobs.get(ns, trial.name)
        if job is None:
            return
        pods = self.jobs.cluster.list_pods(ns, _job_selector(job))
        self.jobs.forget(ns, trial.name)
        for pod in pods:
            try:
                self.jobs.cluster.delete_pod(pod.namespace, pod.name)
            except Exception:
                pass            # reaper/watcher race: already gone

    # ------------------------------------------------------------- kill --

    def kill(self, trial, experiment):
        """Early-stop (or experiment-end) kill: reclaim the trial's
        claimed pods back into the pool, delete only what cannot be
        returned (cold fallbacks), and forget the job record first so no
        reconcile pass runs elastic recovery against the returning pod."""
        ns = experiment.namespace
        job = self.jobs.get(ns, trial.name)
        if job is None:
            return
        now = time.time()
        self._post_spans(ns, trial.name,
                         self.records.get(trial.name, {}).get("pod")
                         or trial.name,
                         [{"name": "trial.stopped", "t0": now, "t1": now,
                           "attrs": {"trial": trial.name,
                                     "state": trial.state.value}}])
        self._stash(trial, experiment)
        pods = self.jobs.cluster.list_pods(ns, _job_selector(job))
        self.jobs.forget(ns, trial.name)
        reclaimed = 0
        for pod in pods:
            if self.pool.reclaim(pod.namespace, pod.name):
                reclaimed += 1
            else:
                try:
                    self.jobs.cluster.delete_pod(pod.namespace, pod.name)
                except Exception:
                    pass            # reaper/reclaim race: already gone
        self.trials_stopped += 1
        self.reclaims += reclaimed
        self.reclaim_noops += len(pods) - reclaimed
        rec = self.records.setdefault(trial.name, {})
        rec["reclaimed_pods"] = reclaimed
        self._metric("inc", "kft_swarm_trials_stopped_total", experiment)
        for _ in range(reclaimed):
            self._metric("inc", "kft_swarm_reclaims_total", experiment)

    # ---------------------------------------------------------- helpers --

    def _stash(self, trial, experiment) -> None:
        """Capture the operator-side trace/phases for a trial while its
        job record still exists — kill() forgets the record, and the
        operator prunes phase reports with it."""
        if self.operator is None:
            return
        rec = self.records.setdefault(trial.name, {})
        try:
            rec["phases"] = self.operator.job_phases(
                experiment.namespace, trial.name)
            rec["trace"] = self.operator.job_trace(
                experiment.namespace, trial.name)
        except Exception:
            pass

    def _metric(self, kind: str, name: str, experiment,
                value: float = 1.0) -> None:
        op = self.operator
        if op is None or getattr(op, "metrics", None) is None:
            return
        labels = {"experiment": experiment.name}
        if kind == "observe":
            op.metrics.observe(name, value, labels)
        else:
            op.metrics.inc(name, labels)

    def _post_spans(self, ns: str, job_name: str, pod_name: str,
                    spans: list) -> None:
        op = self.operator
        if op is None:
            return
        job = self.jobs.get(ns, job_name)
        if job is None:
            return
        try:
            op.heartbeat_post(ns, job_name, pod_name, {"spans": spans},
                              uid=job.uid)
        except Exception:
            pass                    # spans are best-effort, like beats

    def snapshot(self) -> dict:
        return {
            "trials_running": self.trials_running,
            "trials_succeeded": self.trials_succeeded,
            "trials_failed": self.trials_failed,
            "trials_stopped": self.trials_stopped,
            "warm_claims": self.warm_claims,
            "pool_starvation": self.pool_starvation,
            "reclaims": self.reclaims,
            "reclaim_noops": self.reclaim_noops,
        }


def experiment_trace(runner: SwarmTrialRunner, experiment) -> list[dict]:
    """The experiment-level merged trace: every trial's operator job
    trace (stashed at terminal transition for killed/finished trials,
    fetched live otherwise) folded into one span list — one Perfetto
    document with a process row per trial pod. Write it with
    ``obs.export.write_chrome_trace``."""
    from kubeflow_tpu.obs.export import merge_spans

    traces = []
    for trial in experiment.trials:
        rec = runner.records.get(trial.name, {})
        spans = rec.get("trace")
        if not spans and runner.operator is not None:
            try:
                spans = runner.operator.job_trace(
                    experiment.namespace, trial.name)
            except Exception:
                spans = []
        if spans:
            traces.append(spans)
    return merge_spans(*traces) if traces else []
