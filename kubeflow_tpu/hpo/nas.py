"""Neural architecture search ([U] katib:pkg/suggestion/v1beta1/nas/).

Two TPU-stack-native NAS entry points:

- ``ENASSearch`` — an ENAS-style REINFORCE controller as a regular
  Suggestion algorithm (``algorithm.name = "enas"``): the search space is
  the experiment's CATEGORICAL parameters (one per architecture decision,
  values = the op choices), trials evaluate sampled architectures, and the
  controller's per-decision softmax policy is reinforced by trial
  objectives. This is Katib's controller/trial split mapped onto the
  existing Experiment->Suggestion->Trial loop — no new CRDs.

- ``darts_search`` — a DARTS-style one-shot differentiable search in JAX:
  a supernet of mixed ops (continuous relaxation over architecture
  weights alpha), bilevel-optimized (weights on the train split, alpha on
  the validation split), discretized by argmax. One trial's worth of
  compute replaces a population of trials; jit-compiled, runs on CPU in
  tests and on TPU unchanged.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.hpo.search import SearchAlgorithm, _completed
from kubeflow_tpu.hpo.types import ObjectiveGoalType, ParameterType


class ENASSearch(SearchAlgorithm):
    """REINFORCE controller over categorical architecture decisions.

    settings: ``lr`` (policy step, default 0.6), ``baseline_decay``
    (default 0.8), ``temperature`` (sampling softmax temp, default 1.0).
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for p in self.params:
            if p.type not in (ParameterType.CATEGORICAL,
                              ParameterType.DISCRETE):
                raise ValueError(
                    "enas needs categorical/discrete parameters (op choices);"
                    f" {p.name!r} is {p.type.value}")
        self.lr = float(self.settings.get("lr", 0.6))
        self.baseline_decay = float(self.settings.get("baseline_decay", 0.8))
        self.temperature = float(self.settings.get("temperature", 1.0))
        self.theta = {p.name: np.zeros(len(p.values)) for p in self.params}
        self._baseline: Optional[float] = None
        self._learned: set[str] = set()

    def _policy(self, name: str) -> np.ndarray:
        z = self.theta[name] / self.temperature
        z = z - z.max()
        e = np.exp(z)
        return e / e.sum()

    def _reinforce(self, trials) -> None:
        for t in _completed(trials):
            if t.name in self._learned:
                continue
            self._learned.add(t.name)
            reward = float(t.objective_value)
            if self.objective.goal_type == ObjectiveGoalType.MINIMIZE:
                reward = -reward
            if self._baseline is None:
                self._baseline = reward
            adv = reward - self._baseline
            self._baseline = (self.baseline_decay * self._baseline
                              + (1 - self.baseline_decay) * reward)
            for p in self.params:
                if p.name not in t.parameters:
                    continue
                try:
                    chosen = p.values.index(t.parameters[p.name])
                except ValueError:
                    continue
                probs = self._policy(p.name)
                grad = -probs
                grad[chosen] += 1.0            # d log pi / d theta
                self.theta[p.name] += self.lr * adv * grad

    def suggest(self, trials, count):
        self._reinforce(trials)
        out = []
        for _ in range(count):
            arch = {}
            for p in self.params:
                probs = self._policy(p.name)
                idx = int(self.rng.choice(len(p.values), p=probs))
                arch[p.name] = p.values[idx]
            out.append(arch)
        return out


# --------------------------------------------------------------- DARTS ----

# parameter-free candidate ops on [B, D] activations; "zero" lets DARTS
# prune a node away entirely (the DARTS none-op)
CANDIDATE_OPS: dict[str, Callable] = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "square": lambda x: x * x,
    "zero": lambda x: jnp.zeros_like(x),
}


def darts_search(
    x_train: np.ndarray,
    y_train: np.ndarray,
    x_val: np.ndarray,
    y_val: np.ndarray,
    *,
    ops: Sequence[str] = ("identity", "relu", "tanh", "sigmoid", "square"),
    n_nodes: int = 2,
    steps: int = 800,
    warmup: Optional[int] = None,
    lr_w: float = 0.05,
    lr_alpha: float = 0.05,
    seed: int = 0,
) -> tuple[list[str], float]:
    """One-shot DARTS over a sequential cell of ``n_nodes`` mixed ops.

    Supernet: h_0 = x W_in; h_i = sum_o softmax(alpha_i)_o op_o(h_{i-1});
    y_hat = h_n W_out. Weights (W_in/W_out) train on the train split,
    architecture weights alpha on the val split (first-order DARTS
    alternation, alpha frozen for the first ``warmup`` steps so op
    comparisons see trained weights), then each node discretizes to its
    argmax op. Targets are standardized internally so op output scales
    (e.g. square vs tanh) cannot dominate the alpha gradients.

    Returns (selected op names per node, val loss of the DISCRETE
    architecture with retrained weights, in standardized-target units —
    a constant predictor scores ~1.0).
    """
    op_fns = [CANDIDATE_OPS[o] for o in ops]
    mu, sd = y_train.mean(0), y_train.std(0) + 1e-6
    y_train = (y_train - mu) / sd
    y_val = (y_val - mu) / sd
    if warmup is None:
        warmup = steps // 4
    d_in = x_train.shape[1]
    d_out = y_train.shape[1]
    width = int(max(d_in, d_out, 8))
    key = jax.random.key(seed)
    k1, k2 = jax.random.split(key)
    weights = {
        "w_in": jax.random.normal(k1, (d_in, width)) / math.sqrt(d_in),
        "w_out": jax.random.normal(k2, (width, d_out)) / math.sqrt(width),
    }
    alphas = jnp.zeros((n_nodes, len(op_fns)))

    def forward(weights, alphas, x, hard: bool = False):
        h = x @ weights["w_in"]
        for i in range(n_nodes):
            if hard:
                idx = jnp.argmax(alphas[i])
                outs = jnp.stack([f(h) for f in op_fns])
                h = outs[idx]
            else:
                mix = jax.nn.softmax(alphas[i])
                h = sum(m * f(h) for m, f in zip(mix, op_fns))
        return h @ weights["w_out"]

    def loss(weights, alphas, x, y, hard=False):
        pred = forward(weights, alphas, x, hard)
        return jnp.mean((pred - y) ** 2)

    xt, yt = jnp.asarray(x_train), jnp.asarray(y_train)
    xv, yv = jnp.asarray(x_val), jnp.asarray(y_val)

    @jax.jit
    def w_step(weights, alphas):
        gw = jax.grad(loss, argnums=0)(weights, alphas, xt, yt)
        return jax.tree_util.tree_map(lambda w, g: w - lr_w * g, weights, gw)

    @jax.jit
    def a_step(weights, alphas):
        ga = jax.grad(loss, argnums=1)(weights, alphas, xv, yv)
        return alphas - lr_alpha * ga

    for i in range(steps):
        weights = w_step(weights, alphas)
        if i >= warmup:
            alphas = a_step(weights, alphas)

    selected = [ops[int(i)] for i in jnp.argmax(alphas, axis=1)]

    # retrain the weights of the DISCRETE architecture from scratch (the
    # standard DARTS evaluation protocol, miniaturized)
    k3, k4 = jax.random.split(jax.random.key(seed + 1))
    weights = {
        "w_in": jax.random.normal(k3, (d_in, width)) / math.sqrt(d_in),
        "w_out": jax.random.normal(k4, (width, d_out)) / math.sqrt(width),
    }

    @jax.jit
    def retrain_step(weights):
        gw = jax.grad(loss, argnums=0)(weights, alphas, xt, yt, True)
        return jax.tree_util.tree_map(lambda w, g: w - lr_w * g, weights, gw)

    for _ in range(steps):
        weights = retrain_step(weights)
    val = float(loss(weights, alphas, xv, yv, True))
    return selected, val
