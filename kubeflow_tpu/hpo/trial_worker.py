"""HPO trial worker: the swarm's shared-compile trial program.

Run as ``python -m kubeflow_tpu.hpo.trial_worker`` inside a trial pod
(the ``[sys.executable, -m, module]`` form a warm-pool zygote can fork).
The design rule the whole shared-compile leg rests on:

- SCALAR hyperparameters (learning rate ``KFT_TRIAL_LR``, weight decay
  ``KFT_TRIAL_WD``) are passed as TRACED arguments of the jitted train
  step — runtime values, not baked constants — so every trial of a
  structural config lowers to byte-identical HLO and shares ONE
  executable-depot entry (``fingerprint(stage="hpo-trial")``).
- STRUCTURAL hyperparameters (``KFT_TRIAL_WIDTH``/``KFT_TRIAL_DEPTH``)
  change the program's shapes: they legitimately fork the depot key
  (carried in the fingerprint ``extra``) and are counted as distinct
  entries, never a collision.

The trial objective is a deterministic convex toy — gradient descent on
``f(w) = ½‖w‖²`` with the update ``w ← (1 − lr − wd)·w`` — so the loss
curve is an exact function of (lr, wd, step): trials with small lr
plateau high and MedianStop/ASHA stop them mid-run (the reclaim arc),
while the compiled step is a real XLA executable exercising the depot.
Phases (proc_start/imports_done/state_init_done/compile_done/
first_step_done + the ``depot_outcome`` stamp) and the ``trial.load`` /
``trial.step`` spans ride the same heartbeat transport worker_check
uses, so bench decomposes submit→first-step per trial without logs.
"""

from __future__ import annotations

import os
import sys
import time

from kubeflow_tpu.rendezvous.worker_check import _phase


def lowered_step(width: int, depth: int):
    """Lower the trial train step for one structural config. ``lr`` and
    ``wd`` are abstract scalar ARGUMENTS — two trials differing only in
    scalars produce this exact same lowering."""
    import jax
    import jax.numpy as jnp

    def step(w, lr, wd):
        loss = 0.5 * jnp.sum(w * w)
        # d(loss)/dw = w; SGD with decoupled weight decay
        w_next = w - lr * w - wd * w
        return w_next, loss

    f32 = jnp.float32
    return jax.jit(step).lower(
        jax.ShapeDtypeStruct((depth, width), f32),
        jax.ShapeDtypeStruct((), f32),
        jax.ShapeDtypeStruct((), f32))


def main() -> int:
    phases: dict = {}
    _phase(phases, "proc_start")
    import jax

    if os.environ.get("KFT_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_FORCE_PLATFORM"])

    import jax.numpy as jnp

    from kubeflow_tpu.parallel.depot import (
        DepotStats, depot_from_env, load_or_compile,
    )
    from kubeflow_tpu.training.metrics import MetricsWriter

    _phase(phases, "imports_done")

    lr = float(os.environ.get("KFT_TRIAL_LR", "0.1"))
    wd = float(os.environ.get("KFT_TRIAL_WD", "0.0"))
    width = int(os.environ.get("KFT_TRIAL_WIDTH", "8"))
    depth = int(os.environ.get("KFT_TRIAL_DEPTH", "2"))
    steps = int(os.environ.get("KFT_TRAIN_STEPS", "8"))
    step_sleep = float(os.environ.get("KFT_STEP_SLEEP", "0"))

    dstats = DepotStats()
    try:
        depot = depot_from_env(stats=dstats)
    except Exception:
        dstats.inc("fetch_errors")      # fail-open, counted (depot rule)
        depot = None
    w = jnp.ones((depth, width), jnp.float32)
    _phase(phases, "state_init_done")

    # follower trials (KFT_DEPOT_WAIT_S, set by SwarmTrialRunner for all
    # but the first trial of each structural config) wait for the
    # designated publisher's entry instead of racing an identical compile
    wait_s = (float(os.environ.get("KFT_DEPOT_WAIT_S", "0"))
              if depot is not None else 0.0)
    compiled, outcome = load_or_compile(
        lowered_step(width, depth), depot,
        extra=(f"width={width}", f"depth={depth}"),
        stage="hpo-trial", stats=dstats, wait_s=wait_s)
    phases["depot_hit"] = 1.0 if outcome == "hit" else 0.0
    phases["depot_outcome"] = outcome
    _phase(phases, "compile_done",
           extra={"depot": dstats.snapshot()} if depot is not None
           else None)

    metrics_path = os.environ.get("KFT_METRICS_PATH")
    metrics = MetricsWriter(metrics_path) if metrics_path else None
    lr_arr = jnp.asarray(lr, jnp.float32)
    wd_arr = jnp.asarray(wd, jnp.float32)
    loss = float("nan")
    for i in range(steps):
        t_step = time.time()
        w, loss_dev = compiled(w, lr_arr, wd_arr)
        loss = float(loss_dev)
        if i == 0:
            t_now = time.time()
            # trial.load covers fork→ready-to-step (imports + state init
            # + depot fetch/compile); trial.step is the first real step —
            # both posted through the phases transport as explicit spans
            _phase(phases, "first_step_done", extra={"spans": [
                {"name": "trial.load", "t0": phases["proc_start"],
                 "t1": t_step,
                 "attrs": {"depot_outcome": outcome, "width": width,
                           "depth": depth}},
                {"name": "trial.step", "t0": t_step, "t1": t_now,
                 "attrs": {"step": 0}},
            ]})
        if metrics is not None:
            # the OBJECTIVE is width/depth-normalized (starts at exactly
            # 1.0 for every structural config, decays (1-lr-wd)^(2k)) so
            # MedianStop ranks trials by their scalars, not by which
            # structural config happens to have more parameters
            metrics.write(i, loss=loss / (0.5 * depth * width),
                          raw_loss=loss)
        if step_sleep:
            time.sleep(step_sleep)

    print(f"trial done: lr={lr} wd={wd} width={width} depth={depth} "
          f"steps={steps} loss={loss} depot={outcome}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
