"""Experiment controller — reconciles Experiment → suggestions → Trials.

Mirrors the reference's experiment/suggestion/trial controller triangle and
its hot loop (SURVEY.md §3.2: GetSuggestions → create Trial CRs → metrics →
goal/maxTrialCount check), with the TPU-native differences:

- Trials run as JAXJobs through the job layer (JobTrialRunner) or as local
  callables (CallableTrialRunner — the unit-test / `tune()` path).
- Observations come from the native metrics path (MetricsWriter JSONL or a
  direct report callback), not a stdout-scraping sidecar (SURVEY.md §5).
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time
from typing import Callable, Optional

from kubeflow_tpu.api.types import JobSpec
from kubeflow_tpu.controller.reconciler import JobController
from kubeflow_tpu.hpo.earlystopping import make_stopper
from kubeflow_tpu.hpo.service import SuggestionCore
from kubeflow_tpu.hpo.types import (
    Experiment, Observation, Trial, TrialState,
)
from kubeflow_tpu.training.metrics import read_metrics

ReportFn = Callable[..., None]


class TrialRunner:
    """Launch a trial and feed observations back. Non-blocking start; the
    controller polls ``poll`` until the trial finishes."""

    def start(self, trial: Trial, experiment: Experiment) -> None:
        raise NotImplementedError

    def poll(self, trial: Trial, experiment: Experiment) -> None:
        """Update trial.state/observations from the execution backend."""
        raise NotImplementedError

    def kill(self, trial: Trial, experiment: Experiment) -> None:
        pass


class CallableTrialRunner(TrialRunner):
    """Runs ``fn(params, report)`` in a worker thread; ``report(step=, **m)``
    streams intermediate metrics; the return value (or the last reported
    objective metric) is the objective."""

    def __init__(self, fn: Callable, max_workers: int = 8):
        self.fn = fn
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers)
        self._futures: dict[str, concurrent.futures.Future] = {}
        self._stop_flags: dict[str, threading.Event] = {}

    def start(self, trial, experiment):
        stop = threading.Event()
        self._stop_flags[trial.name] = stop

        def report(step: int = 0, **metrics):
            if stop.is_set():
                raise _TrialStopped()
            for k, v in metrics.items():
                trial.observations.append(
                    Observation(metric_name=k, value=float(v), step=step))

        def run():
            return self.fn(dict(trial.parameters), report)

        self._futures[trial.name] = self._pool.submit(run)
        trial.state = TrialState.RUNNING

    def poll(self, trial, experiment):
        fut = self._futures.get(trial.name)
        if fut is None or not fut.done():
            return
        metric = experiment.objective.metric_name
        try:
            result = fut.result()
        except _TrialStopped:
            trial.state = TrialState.EARLY_STOPPED
            finalize_objective(trial, experiment)
            return
        except Exception:
            trial.state = TrialState.FAILED
            return
        finally:
            trial.completion_time = time.time()
        if result is not None:
            trial.observations.append(
                Observation(metric_name=metric, value=float(result)))
        trial.state = TrialState.SUCCEEDED
        finalize_objective(trial, experiment)

    def kill(self, trial, experiment):
        flag = self._stop_flags.get(trial.name)
        if flag:
            flag.set()

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)


class _TrialStopped(Exception):
    pass


def finalize_objective(trial: Trial, experiment: Experiment) -> None:
    """Set trial.objective_value to the best intermediate value — the ONE
    place objective semantics live for both runner kinds."""
    vals = [v for _, v in trial.intermediate(experiment.objective.metric_name)]
    if vals:
        trial.objective_value = (
            min(vals)
            if experiment.objective.goal_type.value == "minimize"
            else max(vals))


class JobTrialRunner(TrialRunner):
    """Trials are jobs in the training layer (the production path).

    ``template(trial_name, params) -> JobSpec`` plays Katib's trialTemplate
    with parameter substitution; the job's workers write metrics to
    ``{metrics_dir}/{trial_name}.jsonl`` via training.MetricsWriter — the
    cross-process observation contract.
    """

    def __init__(self, jobs: JobController,
                 template: Callable[[str, dict], JobSpec],
                 metrics_dir: str):
        self.jobs = jobs
        self.template = template
        self.metrics_dir = metrics_dir
        os.makedirs(metrics_dir, exist_ok=True)

    def metrics_path(self, trial_name: str) -> str:
        return os.path.join(self.metrics_dir, f"{trial_name}.jsonl")

    def _prepare_job(self, job: JobSpec, trial, experiment) -> None:
        pass

    def start(self, trial, experiment):
        job = self.template(trial.name, dict(trial.parameters))
        job.name = trial.name
        # one namespace for submit/poll/kill: the experiment's
        job.namespace = experiment.namespace
        job.labels["experiment"] = experiment.name
        for spec in job.replica_specs.values():
            spec.template.env["KFT_METRICS_PATH"] = self.metrics_path(trial.name)
        # subclass hook, called after env wiring and before submit — the
        # swarm runner shapes per-trial env here (depot follower wait)
        self._prepare_job(job, trial, experiment)
        try:
            self.jobs.submit(job)
        except Exception as e:
            # admission rejection (quota, validation): the trial FAILS —
            # a CREATED trial nothing ever polls would wedge the experiment
            # forever while silently eating parallelism budget
            trial.state = TrialState.FAILED
            trial.completion_time = time.time()
            trial.observations.append(Observation(
                metric_name="admission_error", value=0.0))
            print(f"trial {trial.name}: submission rejected: {e}",
                  flush=True)
            return
        self.jobs.reconcile(job.namespace, job.name)
        trial.state = TrialState.RUNNING

    def poll(self, trial, experiment):
        job = self.jobs.get(experiment.namespace, trial.name)
        if job is None:
            trial.state = TrialState.FAILED
            return
        self.jobs.reconcile(job.namespace, job.name)
        self._sync_observations(trial)
        if not job.status.is_finished():
            return
        trial.completion_time = time.time()
        from kubeflow_tpu.api.types import ConditionType
        if job.status.condition() == ConditionType.SUCCEEDED:
            finalize_objective(trial, experiment)
            if trial.objective_value is not None:
                trial.state = TrialState.SUCCEEDED
            else:
                trial.state = TrialState.FAILED   # succeeded but no metrics
        else:
            trial.state = TrialState.FAILED

    def kill(self, trial, experiment):
        job = self.jobs.get(experiment.namespace, trial.name)
        if job is not None:
            self.jobs.delete(job.namespace, job.name)

    def _sync_observations(self, trial: Trial) -> None:
        recs = read_metrics(self.metrics_path(trial.name))
        trial.observations = [
            Observation(metric_name=k, value=float(v), step=int(r.get("step", 0)),
                        timestamp=r.get("ts", 0.0))
            for r in recs
            for k, v in r.items()
            if k not in ("step", "ts") and isinstance(v, (int, float))
        ]


class ExperimentController:
    """Drives one experiment to completion. ``step()`` is one reconcile pass;
    ``run()`` polls until done (the local/e2e driver, like
    JobController.run_to_completion)."""

    def __init__(self, experiment: Experiment, runner: TrialRunner,
                 core: Optional[SuggestionCore] = None, store=None,
                 trial_seq: int = 0, suggestion_batch: int = 0):
        experiment.validate()
        self.exp = experiment
        self.runner = runner
        self.core = core or SuggestionCore()
        self.core.register(experiment)
        # suggestion batching (ROADMAP 4c): at 100+ parallel trials the
        # trickle of completions would otherwise cost one count=1
        # get_suggestions per launch pass. With suggestion_batch > 0 each
        # draw requests max(budget, suggestion_batch) and the surplus is
        # buffered, so calls amortize to ~launched/batch. Buffered
        # assignments are DELIBERATELY not persisted: on restart the
        # resume() fast-forward replays only the LAUNCHED prefix, so a
        # fresh cursor re-derives the exact buffered sequence —
        # determinism across restart costs nothing. Default 0 keeps the
        # draw-exactly-budget behavior (right for history-conditioned
        # algorithms like TPE/CMA-ES, which want maximal history per
        # draw).
        self.suggestion_batch = suggestion_batch
        self._suggestion_buf: list[dict] = []
        self._search_exhausted = False
        self.suggestion_calls = 0
        self.max_calls_per_pass = 0
        self.stopper = make_stopper(experiment.objective,
                                    experiment.early_stopping)
        # trial_seq is passed on resume so the initial sync below never
        # writes a zeroed cursor over the persisted one (a crash between
        # resume and the first step must not recycle trial names)
        self._trial_seq = trial_seq
        # optional durability: hpo.persistence.ExperimentStore — status +
        # changed trials written through after every reconcile pass
        self.store = store
        if store is not None:
            store.sync(experiment, self._trial_seq)

    @classmethod
    def resume(cls, namespace: str, name: str, runner: TrialRunner, store,
               core: Optional[SuggestionCore] = None,
               suggestion_batch: int = 0) -> "ExperimentController":
        """Reconstruct a controller from the metadata store after a daemon
        restart. In-flight trials died with the previous process and are
        marked KILLED (not FAILED: a crash of the *operator* must not eat
        the experiment's failure budget). Cursor-based suggestion algorithms
        (grid/sobol) are fast-forwarded past the consumed prefix; history-
        conditioned ones (TPE/CMA-ES) re-fit from the restored trials."""
        loaded = store.load(namespace, name)
        if loaded is None:
            raise KeyError(f"experiment {namespace}/{name} not in store")
        exp, seq, _ = loaded
        for t in exp.trials:
            if not t.is_finished():
                t.state = TrialState.KILLED
                t.completion_time = time.time()
        ctl = cls(exp, runner, core, store=store, trial_seq=seq,
                  suggestion_batch=suggestion_batch)
        if exp.trials and not (exp.succeeded or exp.failed):
            # consume (and discard) as many suggestions as were previously
            # LAUNCHED so grid/sobol cursors do not replay duplicates.
            # Suggestions that were only buffered (suggestion_batch
            # prefetch) were never persisted, so the fresh cursor
            # re-derives them next draw — the launched prefix is the
            # whole replay state
            ctl.core.get_suggestions(exp.name, len(exp.trials))
        return ctl

    # one reconcile pass ----------------------------------------------------
    def step(self) -> None:
        self._step()
        if self.store is not None:
            self.store.sync(self.exp, self._trial_seq)

    def _step(self) -> None:
        exp = self.exp
        if exp.succeeded or exp.failed:
            return

        for t in exp.trials:
            if t.state == TrialState.RUNNING:
                self.runner.poll(t, exp)

        if self.stopper is not None:
            for t in exp.trials:
                if t.state == TrialState.RUNNING and \
                        self.stopper.should_stop(t, exp.trials):
                    # settle the state FIRST: polling after kill would see a
                    # deleted job and misreport the trial as FAILED
                    finalize_objective(t, exp)
                    t.state = TrialState.EARLY_STOPPED
                    t.completion_time = time.time()
                    self.runner.kill(t, exp)

        counts = exp.counts()
        # Katib semantics: the experiment fails when the failed-trial count
        # *reaches* the budget (not budget+1); 0 means zero tolerance.
        if counts[TrialState.FAILED] > 0 and \
                counts[TrialState.FAILED] >= exp.max_failed_trial_count:
            exp.failed = True
            exp.completion_reason = "MaxFailedTrialCountExceeded"
            self._kill_running()
            return
        best = exp.best_trial
        if best is not None and exp.objective.reached(best.objective_value):
            exp.succeeded = True
            exp.completion_reason = "GoalReached"
            self._kill_running()
            return
        launched = len(exp.trials)
        finished = sum(1 for t in exp.trials if t.is_finished())
        if launched >= exp.max_trial_count and finished == launched:
            exp.succeeded = best is not None
            exp.failed = best is None
            exp.completion_reason = "MaxTrialCountReached"
            return

        running = counts[TrialState.RUNNING] + counts[TrialState.CREATED]
        budget = min(exp.parallel_trial_count - running,
                     exp.max_trial_count - launched)
        if budget > 0:
            calls = 0
            if len(self._suggestion_buf) < budget \
                    and not self._search_exhausted:
                want = max(budget, self.suggestion_batch) \
                    - len(self._suggestion_buf)
                got = self.core.get_suggestions(exp.name, want)
                calls += 1
                self.suggestion_calls += 1
                if len(got) < want:
                    # a short draw means a finite space (e.g. grid) is
                    # fully enumerated — never ask again
                    self._search_exhausted = True
                self._suggestion_buf.extend(got)
            self.max_calls_per_pass = max(self.max_calls_per_pass, calls)
            suggestions = self._suggestion_buf[:budget]
            del self._suggestion_buf[:budget]
            if not suggestions and running == 0 and finished == launched:
                # finite search space (e.g. grid) enumerated before
                # max_trial_count: the experiment is done, not stuck
                exp.succeeded = best is not None
                exp.failed = best is None
                exp.completion_reason = "SearchSpaceExhausted"
                return
            for assignment in suggestions:
                self._trial_seq += 1
                trial = Trial(name=f"{exp.name}-trial-{self._trial_seq}",
                              parameters=assignment)
                exp.trials.append(trial)
                self.runner.start(trial, exp)

    def run(self, timeout: float = 300.0, poll: float = 0.02) -> Experiment:
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.step()
            if self.exp.succeeded or self.exp.failed:
                return self.exp
            time.sleep(poll)
        raise TimeoutError(f"experiment {self.exp.name} did not finish")

    def _kill_running(self):
        for t in self.exp.trials:
            if t.state == TrialState.RUNNING:
                self.runner.kill(t, self.exp)
                t.state = TrialState.KILLED
