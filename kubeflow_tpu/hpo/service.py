"""Suggestion + observation-log services.

The reference runs suggestions and the observation DB as separate gRPC
services (Katib: per-experiment suggestion Deployment + katib-db-manager →
MySQL; SURVEY.md §2.3, §3.2). Here the same two API contracts are exposed as
a single length-prefixed-JSON-over-TCP service (no grpc codegen in this
environment) with an in-process core the controller can also call directly:

- ``GetSuggestions {experiment, count}`` → assignments
- ``ReportObservationLog {trial, metric, value, step}``
- ``GetObservationLog {trial}`` → observations
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Optional

from kubeflow_tpu.hpo.search import SearchAlgorithm, make_algorithm
from kubeflow_tpu.hpo.types import Experiment, Observation, Trial


class ObservationLog:
    """In-memory/db-manager-equivalent observation store, keyed by trial."""

    def __init__(self):
        self._log: dict[str, list[Observation]] = {}
        self._lock = threading.Lock()

    def report(self, trial: str, metric: str, value: float, step: int = 0):
        with self._lock:
            self._log.setdefault(trial, []).append(
                Observation(metric_name=metric, value=float(value), step=int(step))
            )

    def get(self, trial: str) -> list[Observation]:
        with self._lock:
            return list(self._log.get(trial, []))


class SuggestionCore:
    """In-process implementation of both API contracts."""

    def __init__(self):
        self._algos: dict[str, SearchAlgorithm] = {}
        self._experiments: dict[str, Experiment] = {}
        self.observations = ObservationLog()
        self._lock = threading.Lock()
        # service-side amortization counters (ROADMAP 4c): at 100+
        # parallel trials the controller must batch its draws —
        # served_total/calls_total is the measured amortization factor
        self.calls_total = 0
        self.served_total = 0

    def register(self, exp: Experiment) -> None:
        with self._lock:
            if exp.name not in self._algos:
                self._algos[exp.name] = make_algorithm(exp)
                self._experiments[exp.name] = exp

    def get_suggestions(self, experiment: str, count: int,
                        trials: Optional[list[Trial]] = None) -> list[dict]:
        # algorithms are stateful (grid cursor, CMA-ES mean/C, RNGs): the
        # lock must span suggest() so concurrent server handlers don't race
        with self._lock:
            algo = self._algos[experiment]
            exp = self._experiments[experiment]
            out = algo.suggest(
                trials if trials is not None else exp.trials, count)
            self.calls_total += 1
            self.served_total += len(out)
            return out

    def counters(self) -> dict:
        with self._lock:
            return {"calls_total": self.calls_total,
                    "served_total": self.served_total}

    # -- wire dispatch ------------------------------------------------------
    def handle(self, req: dict[str, Any]) -> dict[str, Any]:
        method = req.get("method")
        if method == "GetSuggestions":
            return {"assignments": self.get_suggestions(
                req["experiment"], int(req.get("count", 1)))}
        if method == "ReportObservationLog":
            self.observations.report(
                req["trial"], req["metric"], req["value"], req.get("step", 0))
            return {"ok": True}
        if method == "GetObservationLog":
            return {"observations": [
                {"metric": o.metric_name, "value": o.value, "step": o.step}
                for o in self.observations.get(req["trial"])
            ]}
        return {"error": f"unknown method {method!r}"}


def _recv_msg(sock: socket.socket) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return buf


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


class SuggestionServer:
    """TCP façade over SuggestionCore (the suggestion-Deployment equivalent)."""

    def __init__(self, core: SuggestionCore, host: str = "127.0.0.1",
                 port: int = 0):
        self.core = core
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    raw = _recv_msg(self.request)
                    if raw is None:
                        return
                    try:
                        resp = outer.core.handle(json.loads(raw))
                    except Exception as e:   # surface, don't kill the server
                        resp = {"error": str(e)}
                    _send_msg(self.request, json.dumps(resp).encode())

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class SuggestionClient:
    """Client for SuggestionServer; same calls as the in-process core."""

    def __init__(self, address: tuple[str, int]):
        self._sock = socket.create_connection(address)
        self._lock = threading.Lock()

    def _call(self, req: dict) -> dict:
        with self._lock:
            _send_msg(self._sock, json.dumps(req).encode())
            raw = _recv_msg(self._sock)
        if raw is None:
            raise ConnectionError("suggestion server closed connection")
        resp = json.loads(raw)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def get_suggestions(self, experiment: str, count: int) -> list[dict]:
        return self._call({"method": "GetSuggestions",
                           "experiment": experiment, "count": count})["assignments"]

    def report_observation(self, trial: str, metric: str, value: float,
                           step: int = 0):
        self._call({"method": "ReportObservationLog", "trial": trial,
                    "metric": metric, "value": value, "step": step})

    def get_observations(self, trial: str) -> list[dict]:
        return self._call({"method": "GetObservationLog",
                           "trial": trial})["observations"]

    def close(self):
        self._sock.close()
