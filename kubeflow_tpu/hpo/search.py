"""Suggestion algorithms — the Katib suggestion-service catalogue rebuilt.

Parity targets (SURVEY.md §2.3 'Suggestion services'): random, grid, sobol,
TPE (hyperopt equivalent), CMA-ES, hyperband/ASHA (as a scheduler in
earlystopping.py). All are pure-numpy/scipy — no external HPO deps — and all
work over the unit cube via ParameterSpec.to_unit/from_unit, so every
algorithm supports double/int/discrete/categorical (CMA-ES numeric-only).

The interface mirrors the reference's gRPC ``Suggestion.GetSuggestions``:
``suggest(experiment_history, count) -> list[assignment]``.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Optional, Sequence

import numpy as np
from scipy.stats import qmc

from kubeflow_tpu.hpo.types import (
    Experiment, ObjectiveSpec, ParameterSpec, ParameterType, Trial, TrialState,
)

Assignment = dict[str, Any]


def _completed(trials: Sequence[Trial]) -> list[Trial]:
    return [t for t in trials
            if t.state == TrialState.SUCCEEDED and t.objective_value is not None]


class SearchAlgorithm:
    def __init__(self, params: list[ParameterSpec], objective: ObjectiveSpec,
                 settings: Optional[dict] = None, seed: int = 0):
        self.params = params
        self.objective = objective
        self.settings = settings or {}
        self.rng = np.random.default_rng(self.settings.get("seed", seed))

    def suggest(self, trials: Sequence[Trial], count: int) -> list[Assignment]:
        raise NotImplementedError

    # helpers
    def _random_assignment(self) -> Assignment:
        return {p.name: p.from_unit(float(self.rng.random()))
                for p in self.params}

    def _to_units(self, assignment: Assignment) -> np.ndarray:
        return np.array([p.to_unit(assignment[p.name]) for p in self.params])


class RandomSearch(SearchAlgorithm):
    def suggest(self, trials, count):
        return [self._random_assignment() for _ in range(count)]


class GridSearch(SearchAlgorithm):
    """Exhaustive cartesian grid; ``settings['points_per_dim']`` controls
    continuous-dimension resolution (default 4)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        n = int(self.settings.get("points_per_dim", 4))
        axes = [p.grid(n) for p in self.params]
        self._grid = [
            {p.name: v for p, v in zip(self.params, combo)}
            for combo in itertools.product(*axes)
        ]
        self._next = 0

    def suggest(self, trials, count):
        out = self._grid[self._next:self._next + count]
        self._next += len(out)
        return [dict(a) for a in out]


class SobolSearch(SearchAlgorithm):
    """Quasi-random low-discrepancy sweep (scipy Sobol engine)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._engine = qmc.Sobol(
            d=len(self.params), scramble=True,
            seed=int(self.settings.get("seed", 0)),
        )

    def suggest(self, trials, count):
        pts = self._engine.random(count)
        return [
            {p.name: p.from_unit(float(u)) for p, u in zip(self.params, row)}
            for row in pts
        ]


class TPESearch(SearchAlgorithm):
    """Tree-structured Parzen Estimator (the hyperopt-equivalent).

    Split completed trials into good/bad at the gamma quantile of the
    objective; model each split with a per-dimension Parzen (Gaussian KDE in
    unit space, categorical via smoothed counts); sample candidates from
    l(x) (good) and rank by l(x)/g(x).
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.n_startup = int(self.settings.get("n_startup_trials", 8))
        self.gamma = float(self.settings.get("gamma", 0.25))
        self.n_candidates = int(self.settings.get("n_candidates", 24))

    def suggest(self, trials, count):
        done = _completed(trials)
        if len(done) < self.n_startup:
            return [self._random_assignment() for _ in range(count)]
        sign = 1.0 if self.objective.goal_type.value == "minimize" else -1.0
        ranked = sorted(done, key=lambda t: sign * t.objective_value)
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        good = np.stack([self._to_units(t.parameters) for t in ranked[:n_good]])
        bad = np.stack([self._to_units(t.parameters) for t in ranked[n_good:]])

        out = []
        for _ in range(count):
            cands = self._sample_from(good, self.n_candidates)
            scores = self._log_kde(cands, good) - self._log_kde(cands, bad)
            best = cands[int(np.argmax(scores))]
            out.append({p.name: p.from_unit(float(u))
                        for p, u in zip(self.params, best)})
        return out

    def _bandwidth(self, data: np.ndarray) -> np.ndarray:
        n = max(2, data.shape[0])
        # Scott's rule per dimension, floored so the KDE keeps exploring
        bw = data.std(axis=0) * n ** (-1.0 / (4 + data.shape[1]))
        return np.maximum(bw, 0.08)

    def _sample_from(self, data: np.ndarray, n: int) -> np.ndarray:
        bw = self._bandwidth(data)
        idx = self.rng.integers(0, data.shape[0], size=n)
        pts = data[idx] + self.rng.normal(size=(n, data.shape[1])) * bw
        return np.clip(pts, 0.0, 1.0)

    def _log_kde(self, x: np.ndarray, data: np.ndarray) -> np.ndarray:
        if data.shape[0] == 0:
            return np.zeros(x.shape[0])
        bw = self._bandwidth(data)
        # [n_x, n_data, d]
        z = (x[:, None, :] - data[None, :, :]) / bw
        logp = -0.5 * (z ** 2).sum(-1) - np.log(bw).sum()
        return np.logaddexp.reduce(logp, axis=1) - math.log(data.shape[0])


class CMAESSearch(SearchAlgorithm):
    """(mu/mu_w, lambda) CMA-ES in the unit cube. Numeric parameters only."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for p in self.params:
            if p.type == ParameterType.CATEGORICAL:
                raise ValueError("cmaes does not support categorical parameters")
        d = len(self.params)
        self.d = d
        self.mean = np.full(d, 0.5)
        self.sigma = float(self.settings.get("sigma", 0.3))
        self.lam = int(self.settings.get("population", 4 + int(3 * math.log(d + 1))))
        self.mu = self.lam // 2
        w = np.log(self.mu + 0.5) - np.log(np.arange(1, self.mu + 1))
        self.weights = w / w.sum()
        self.mueff = 1.0 / (self.weights ** 2).sum()
        self.cc = (4 + self.mueff / d) / (d + 4 + 2 * self.mueff / d)
        self.cs = (self.mueff + 2) / (d + self.mueff + 5)
        self.c1 = 2 / ((d + 1.3) ** 2 + self.mueff)
        self.cmu = min(1 - self.c1, 2 * (self.mueff - 2 + 1 / self.mueff)
                       / ((d + 2) ** 2 + self.mueff))
        self.damps = 1 + 2 * max(0, math.sqrt((self.mueff - 1) / (d + 1)) - 1) + self.cs
        self.pc = np.zeros(d)
        self.ps = np.zeros(d)
        self.C = np.eye(d)
        self.chiN = math.sqrt(d) * (1 - 1 / (4 * d) + 1 / (21 * d ** 2))
        self._consumed: set[str] = set()   # trial names already used
        self._generation = 0

    def suggest(self, trials, count):
        self._maybe_update(trials)
        out = []
        for _ in range(count):
            z = self.rng.normal(size=self.d)
            try:
                A = np.linalg.cholesky(self.C)
            except np.linalg.LinAlgError:
                self.C = np.eye(self.d)
                A = np.eye(self.d)
            x = np.clip(self.mean + self.sigma * (A @ z), 0.0, 1.0)
            a = {p.name: p.from_unit(float(u)) for p, u in zip(self.params, x)}
            out.append(a)
        return out

    def _maybe_update(self, trials):
        # trials complete out of creation order under parallelism: track
        # consumption by name, not by index
        new = [t for t in _completed(trials) if t.name not in self._consumed]
        if len(new) < self.lam:
            return
        batch = new[:self.lam]
        self._consumed.update(t.name for t in batch)
        self._generation += 1
        sign = 1.0 if self.objective.goal_type.value == "minimize" else -1.0
        batch = sorted(batch, key=lambda t: sign * t.objective_value)[:self.mu]
        xs = np.stack([self._to_units(t.parameters) for t in batch])
        old_mean = self.mean.copy()
        self.mean = self.weights @ xs
        try:
            invsqrtC = np.linalg.inv(np.linalg.cholesky(self.C)).T
        except np.linalg.LinAlgError:
            self.C = np.eye(self.d)
            invsqrtC = np.eye(self.d)
        y = (self.mean - old_mean) / max(self.sigma, 1e-12)
        self.ps = (1 - self.cs) * self.ps + math.sqrt(
            self.cs * (2 - self.cs) * self.mueff) * (invsqrtC @ y)
        hsig = (np.linalg.norm(self.ps)
                / math.sqrt(1 - (1 - self.cs) ** (2 * self._generation))
                / self.chiN) < 1.4 + 2 / (self.d + 1)
        self.pc = (1 - self.cc) * self.pc + hsig * math.sqrt(
            self.cc * (2 - self.cc) * self.mueff) * y
        artmp = (xs - old_mean) / max(self.sigma, 1e-12)
        self.C = ((1 - self.c1 - self.cmu) * self.C
                  + self.c1 * (np.outer(self.pc, self.pc)
                               + (not hsig) * self.cc * (2 - self.cc) * self.C)
                  + self.cmu * (artmp.T * self.weights) @ artmp)
        self.sigma *= math.exp(
            (self.cs / self.damps) * (np.linalg.norm(self.ps) / self.chiN - 1))
        self.sigma = float(np.clip(self.sigma, 1e-3, 1.0))


ALGORITHMS = {
    "random": RandomSearch,
    "grid": GridSearch,
    "sobol": SobolSearch,
    "tpe": TPESearch,
    "cmaes": CMAESSearch,
    # hyperband = random sampling + ASHA early stopping (earlystopping.py);
    # registered so AlgorithmSpec(name="hyperband") resolves.
    "hyperband": RandomSearch,
}


def make_algorithm(exp: Experiment) -> SearchAlgorithm:
    name = exp.algorithm.name
    if name == "enas":
        # NAS controller lives in hpo.nas (imported lazily: it pulls in jax)
        from kubeflow_tpu.hpo.nas import ENASSearch

        return ENASSearch(exp.parameters, exp.objective,
                          exp.algorithm.settings)
    if name not in ALGORITHMS:
        raise ValueError(
            f"unknown algorithm {name!r}; have {sorted(ALGORITHMS) + ['enas']}")
    return ALGORITHMS[name](exp.parameters, exp.objective, exp.algorithm.settings)
