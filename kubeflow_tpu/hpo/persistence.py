"""Durable HPO: experiments/trials/observations in the metadata store.

The reference persists Katib state in MySQL behind katib-db-manager
(SURVEY.md §2.3 'DB-manager persistence', [U] katib:pkg/db/v1beta1/). Here
the SAME lineage store that backs pipelines is the database — an experiment
is a Context, each trial is an Execution associated with it, and the
experiment's live status rides a dedicated status Execution (contexts are
immutable in MLMD-style stores; executions are updatable). Works against
either backend: the in-proc ``MetadataStore`` (WAL-replayed on restart) or
the native C++ server via ``MetadataClient``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

from kubeflow_tpu.hpo.types import (
    AlgorithmSpec, EarlyStoppingSpec, Experiment, ObjectiveSpec, Observation,
    ParameterSpec, ParameterType, ObjectiveGoalType, ResumePolicy, Trial,
    TrialState,
)

EXPERIMENT_TYPE = "hpo_experiment"
STATUS_TYPE = "hpo_experiment_status"
TRIAL_TYPE = "hpo_trial"


# --------------------------------------------------------------- serialization

def experiment_spec_to_dict(exp: Experiment) -> dict:
    return {
        "name": exp.name,
        "namespace": exp.namespace,
        "parameters": [dataclasses.asdict(p) for p in exp.parameters],
        "objective": dataclasses.asdict(exp.objective),
        "algorithm": dataclasses.asdict(exp.algorithm),
        "early_stopping": (dataclasses.asdict(exp.early_stopping)
                           if exp.early_stopping else None),
        "parallel_trial_count": exp.parallel_trial_count,
        "max_trial_count": exp.max_trial_count,
        "max_failed_trial_count": exp.max_failed_trial_count,
        "resume_policy": exp.resume_policy.value,
    }


def experiment_from_dict(d: dict) -> Experiment:
    params = []
    for p in d["parameters"]:
        p = dict(p)
        p["type"] = ParameterType(p["type"])
        params.append(ParameterSpec(**p))
    obj = dict(d["objective"])
    obj["goal_type"] = ObjectiveGoalType(obj["goal_type"])
    es = None
    if d.get("early_stopping"):
        es = EarlyStoppingSpec(**d["early_stopping"])
    return Experiment(
        name=d["name"], namespace=d.get("namespace", "default"),
        parameters=params, objective=ObjectiveSpec(**obj),
        algorithm=AlgorithmSpec(**d["algorithm"]), early_stopping=es,
        parallel_trial_count=d["parallel_trial_count"],
        max_trial_count=d["max_trial_count"],
        max_failed_trial_count=d["max_failed_trial_count"],
        resume_policy=ResumePolicy(d.get("resume_policy", "Never")),
    )


def _trial_props(trial: Trial) -> dict:
    return {
        "parameters": json.dumps(trial.parameters),
        "objective_value": json.dumps(trial.objective_value),
        "observations": json.dumps([
            [o.metric_name, o.value, o.step, o.timestamp]
            for o in trial.observations
        ]),
        "start_time": trial.start_time,
        "completion_time": json.dumps(trial.completion_time),
    }


def _trial_from_execution(name: str, ex) -> Trial:
    p = ex.properties
    t = Trial(
        name=name,
        parameters=json.loads(p.get("parameters", "{}")),
        state=TrialState(ex.state),
        objective_value=json.loads(str(p.get("objective_value", "null"))),
        start_time=float(p.get("start_time", 0.0)),
        completion_time=json.loads(str(p.get("completion_time", "null"))),
    )
    t.observations = [
        Observation(metric_name=m, value=v, step=s, timestamp=ts)
        for m, v, s, ts in json.loads(p.get("observations", "[]"))
    ]
    return t


# --------------------------------------------------------------------- store

class ExperimentStore:
    """Write-through persistence for experiments over a metadata backend
    (``metadata.store.MetadataStore`` or ``metadata.client.MetadataClient``
    — same duck-typed surface). Records are keyed by
    ``{namespace}/{name}`` so experiments are namespace-scoped like every
    other resource."""

    def __init__(self, backend):
        self.backend = backend
        self._ctx_ids: dict[str, int] = {}
        self._status_ids: dict[str, int] = {}
        self._trial_ids: dict[tuple[str, str], int] = {}
        # change cache: trial -> (state, n_observations, objective_value)
        self._trial_sig: dict[tuple[str, str], tuple] = {}

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    # -- experiment ---------------------------------------------------------

    def create_experiment(self, exp: Experiment,
                          extra_props: Optional[dict] = None) -> int:
        """Record the (immutable) spec + a mutable status execution."""
        key = self._key(exp.namespace, exp.name)
        props = {"spec": json.dumps(experiment_spec_to_dict(exp))}
        props.update(extra_props or {})
        cid = self.backend.put_context(EXPERIMENT_TYPE, key, properties=props)
        self._ctx_ids[key] = cid
        sid = self._status_execution(key, cid)
        self.backend.update_execution(
            sid, state="RUNNING",
            properties={"trial_seq": 0, "completion_reason": ""})
        return cid

    def _status_execution(self, key: str, cid: int) -> int:
        if key not in self._status_ids:
            ctx_execs = self.backend.executions_in_context(cid)
            for ex in ctx_execs:
                if ex.type == STATUS_TYPE:
                    self._status_ids[key] = ex.id
                    break
            else:
                sid = self.backend.put_execution(
                    STATUS_TYPE, name=f"{key}/status", state="RUNNING")
                self.backend.associate(cid, sid)
                self._status_ids[key] = sid
        return self._status_ids[key]

    def sync(self, exp: Experiment, trial_seq: int) -> None:
        """Persist status + any trial whose state/observations changed."""
        ekey = self._key(exp.namespace, exp.name)
        cid = self._ctx_ids.get(ekey)
        if cid is None:
            cid = self.create_experiment(exp)
        for trial in exp.trials:
            key = (ekey, trial.name)
            sig = (trial.state.value, len(trial.observations),
                   trial.objective_value)
            if self._trial_sig.get(key) == sig:
                continue
            tid = self._trial_ids.get(key)
            if tid is None:
                tid = self.backend.put_execution(
                    TRIAL_TYPE, name=f"{ekey}/{trial.name}",
                    state=trial.state.value, properties=_trial_props(trial))
                self.backend.associate(cid, tid)
                self._trial_ids[key] = tid
            else:
                self.backend.update_execution(
                    tid, state=trial.state.value,
                    properties=_trial_props(trial))
            self._trial_sig[key] = sig
        state = ("SUCCEEDED" if exp.succeeded
                 else "FAILED" if exp.failed else "RUNNING")
        self.backend.update_execution(
            self._status_execution(ekey, cid), state=state,
            properties={"trial_seq": trial_seq,
                        "completion_reason": exp.completion_reason})

    def mark_deleted(self, namespace: str, name: str) -> None:
        """Tombstone an experiment so a daemon restart never resumes it."""
        key = self._key(namespace, name)
        ctx = self.backend.context_by_name(EXPERIMENT_TYPE, key)
        if ctx is None:
            return
        self.backend.update_execution(
            self._status_execution(key, ctx.id), state="DELETED",
            properties={"completion_reason": "Deleted"})

    # -- load / resume ------------------------------------------------------

    def list_experiments(self) -> list[tuple[str, str]]:
        """-> [(namespace, name)]. Uses the in-proc backend's context table;
        remote callers track names via the operator registry."""
        contexts = getattr(self.backend, "contexts", None)
        if contexts is None:
            return []
        return [tuple(c.name.split("/", 1)) for c in contexts.values()
                if c.type == EXPERIMENT_TYPE and "/" in c.name]

    def load(self, namespace: str, name: str
             ) -> Optional[tuple[Experiment, int, dict]]:
        """-> (experiment with trials + status, trial_seq, context_props).
        A DELETED tombstone loads with failed=True/reason 'Deleted' so no
        caller resumes it."""
        ekey = self._key(namespace, name)
        ctx = self.backend.context_by_name(EXPERIMENT_TYPE, ekey)
        if ctx is None:
            return None
        exp = experiment_from_dict(json.loads(ctx.properties["spec"]))
        self._ctx_ids[ekey] = ctx.id
        trial_seq = 0
        prefix = f"{ekey}/"
        for ex in self.backend.executions_in_context(ctx.id):
            if ex.type == STATUS_TYPE:
                self._status_ids[ekey] = ex.id
                trial_seq = int(ex.properties.get("trial_seq", 0))
                exp.succeeded = ex.state == "SUCCEEDED"
                exp.failed = ex.state in ("FAILED", "DELETED")
                exp.completion_reason = ex.properties.get(
                    "completion_reason", "")
            elif ex.type == TRIAL_TYPE and ex.name.startswith(prefix):
                tname = ex.name[len(prefix):]
                trial = _trial_from_execution(tname, ex)
                exp.trials.append(trial)
                key = (ekey, trial.name)
                self._trial_ids[key] = ex.id
                self._trial_sig[key] = (
                    trial.state.value, len(trial.observations),
                    trial.objective_value)
        exp.trials.sort(key=lambda t: t.start_time)
        return exp, trial_seq, dict(ctx.properties)
