"""Experiment manager: the HPO layer inside the operator daemon.

The reference runs Katib as its own controller-manager Deployment next to
the training operator; this package's single-binary stance (SURVEY.md §7)
puts the experiment reconcile loop inside the SAME daemon process as the
job controller — one more ticker on the operator's control loop. Durable
state lives in the metadata store (hpo.persistence), so a daemon restart
resumes every unfinished experiment from disk, Katib's resumePolicy:
LongRunning behavior without a separate DB tier.

Trial templates are JobSpec YAML with ``${param}`` placeholders — Katib's
trialTemplate parameter substitution ([U] katib trialTemplate), rendered
per trial and submitted through the shared JobController.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from kubeflow_tpu.api.types import JobSpec, from_yaml
from kubeflow_tpu.controller.reconciler import JobController
from kubeflow_tpu.hpo.controller import ExperimentController, JobTrialRunner
from kubeflow_tpu.hpo.persistence import ExperimentStore
from kubeflow_tpu.hpo.types import Experiment


def render_trial_template(template_yaml: str) -> Callable[[str, dict], JobSpec]:
    """trialTemplate substitution: every ``${name}`` in the YAML is replaced
    with the assignment's value, then parsed into a JobSpec."""

    def template(trial_name: str, params: dict) -> JobSpec:
        text = template_yaml
        for k, v in params.items():
            text = text.replace("${" + k + "}", str(v))
        job = from_yaml(text)
        # numeric substitutions re-parse as YAML numbers; env is str->str
        for spec in job.replica_specs.values():
            spec.template.env = {
                k: str(v) for k, v in spec.template.env.items()}
        return job

    return template


class ExperimentManager:
    """Owns the live ExperimentControllers of one daemon process."""

    def __init__(self, jobs: JobController, metrics_dir: str,
                 store: Optional[ExperimentStore] = None,
                 swarm_pool=None, structural_keys=()):
        self.jobs = jobs
        self.metrics_dir = metrics_dir
        self.store = store
        # trial-swarm mode (hpo/swarm.py): with a warm pool attached,
        # trials claim standbys, share depot entries, and early-stopped
        # pods are reclaimed. ``operator`` is attached by the Operator at
        # construction (span/metric sink); ``structural_keys`` names the
        # hyperparameters that fork the compiled program.
        self.swarm_pool = swarm_pool
        self.structural_keys = tuple(structural_keys)
        self.operator = None
        self.controllers: dict[tuple[str, str], ExperimentController] = {}
        self._lock = threading.RLock()

    def _runner(self, template_yaml: str) -> JobTrialRunner:
        template = render_trial_template(template_yaml)
        if self.swarm_pool is not None:
            from kubeflow_tpu.hpo.swarm import SwarmTrialRunner

            return SwarmTrialRunner(
                self.jobs, template, self.metrics_dir,
                pool=self.swarm_pool, operator=self.operator,
                structural_keys=self.structural_keys)
        return JobTrialRunner(self.jobs, template, self.metrics_dir)

    def submit(self, exp: Experiment, trial_template: str
               ) -> ExperimentController:
        with self._lock:
            key = (exp.namespace, exp.name)
            if key in self.controllers:
                raise ValueError(f"experiment {key} already exists")
            if self.store is not None:
                # spec + template recorded BEFORE the first reconcile so a
                # crash at any later point can reconstruct the controller
                self.store.create_experiment(
                    exp, extra_props={"trial_template": trial_template})
            ctl = ExperimentController(exp, self._runner(trial_template),
                                       store=self.store)
            self.controllers[key] = ctl
            return ctl

    def resume_persisted(self) -> list[tuple[str, str]]:
        """Reconstruct controllers for every unfinished stored experiment
        (daemon-restart path). Returns resumed (namespace, name) keys."""
        if self.store is None:
            return []
        resumed = []
        with self._lock:
            for ns, name in self.store.list_experiments():
                key = (ns, name)
                if key in self.controllers:
                    continue
                # one corrupt/incompatible stored record (older WAL, renamed
                # enum, tightened validation) must not crash-loop the whole
                # daemon: skip it and keep booting
                try:
                    loaded = self.store.load(ns, name)
                    if loaded is None:
                        continue
                    exp, _, props = loaded
                    if exp.succeeded or exp.failed:
                        continue
                    template = props.get("trial_template")
                    if not template:
                        continue
                    self.controllers[key] = ExperimentController.resume(
                        ns, name, self._runner(template), self.store)
                    resumed.append(key)
                except Exception as e:
                    print(f"resume_persisted: skipping {ns}/{name}: "
                          f"{type(e).__name__}: {e}", flush=True)
        return resumed

    def tick(self) -> None:
        """One reconcile pass over every live experiment (operator ticker)."""
        with self._lock:
            ctls = list(self.controllers.values())
        for ctl in ctls:
            if not (ctl.exp.succeeded or ctl.exp.failed):
                ctl.step()

    def get(self, namespace: str, name: str) -> Optional[Experiment]:
        with self._lock:
            ctl = self.controllers.get((namespace, name))
            return ctl.exp if ctl else None

    def list(self) -> list[Experiment]:
        with self._lock:
            return [c.exp for c in self.controllers.values()]

    def delete(self, namespace: str, name: str) -> None:
        with self._lock:
            ctl = self.controllers.pop((namespace, name), None)
        if ctl is not None:
            ctl._kill_running()
        if self.store is not None:
            # tombstone: a restart must not resurrect a deleted experiment
            self.store.mark_deleted(namespace, name)
