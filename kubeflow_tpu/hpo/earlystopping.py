"""Early-stopping rules — median-stop (Katib's medianstop service,
SURVEY.md §2.3) plus ASHA/successive-halving (the hyperband scheduler half).

Both consume intermediate observations from the native metrics path and
return a stop/continue decision per running trial; no sidecar involved.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

from kubeflow_tpu.hpo.types import (
    EarlyStoppingSpec, ObjectiveSpec, Trial, TrialState,
)


class EarlyStopper:
    def __init__(self, objective: ObjectiveSpec, spec: EarlyStoppingSpec):
        self.objective = objective
        self.spec = spec

    def should_stop(self, trial: Trial, all_trials: Sequence[Trial]) -> bool:
        raise NotImplementedError


class MedianStop(EarlyStopper):
    """Stop a trial whose best-so-far is worse than the median of other
    trials' running averages at the same step."""

    def should_stop(self, trial, all_trials):
        metric = self.objective.metric_name
        points = trial.intermediate(metric)
        if not points:
            return False
        step = points[-1][0]
        if step < self.spec.start_step:
            return False
        others = []
        for t in all_trials:
            if t.name == trial.name:
                continue
            if t.state not in (TrialState.SUCCEEDED, TrialState.RUNNING,
                               TrialState.EARLY_STOPPED):
                continue
            upto = [v for s, v in t.intermediate(metric) if s <= step]
            if upto:
                others.append(sum(upto) / len(upto))
        if len(others) < self.spec.min_trials_required:
            return False
        others.sort()
        median = others[len(others) // 2]
        vals = [v for _, v in points]
        best = (min(vals) if self.objective.goal_type.value == "minimize"
                else max(vals))
        return not self.objective.better(best, median) and best != median


class ASHA(EarlyStopper):
    """Asynchronous successive halving: at each rung (min_resource * eta^k),
    a trial survives only if it is in the top 1/eta of trials that reached
    that rung. Random search + ASHA == hyperband-class behavior."""

    def __init__(self, objective, spec):
        super().__init__(objective, spec)
        self.eta = float(spec.settings.get("eta", 3))
        self.min_resource = int(spec.settings.get("min_resource", 1))
        self.max_resource = int(spec.settings.get("max_resource", 81))

    def _rungs(self):
        r = self.min_resource
        while r < self.max_resource:
            yield r
            r = int(math.ceil(r * self.eta))

    def _value_at(self, t: Trial, rung: int) -> Optional[float]:
        upto = [v for s, v in t.intermediate(self.objective.metric_name)
                if s <= rung]
        if not upto:
            return None
        return (min(upto) if self.objective.goal_type.value == "minimize"
                else max(upto))

    def should_stop(self, trial, all_trials):
        points = trial.intermediate(self.objective.metric_name)
        if not points:
            return False
        step = points[-1][0]
        for rung in self._rungs():
            if step < rung:
                break
            mine = self._value_at(trial, rung)
            if mine is None:
                continue
            peers = []
            for t in all_trials:
                v = self._value_at(t, rung)
                if v is not None:
                    peers.append(v)
            if len(peers) < max(2, int(self.eta)):
                continue
            sign = 1 if self.objective.goal_type.value == "minimize" else -1
            peers.sort(key=lambda v: sign * v)
            k = max(1, int(len(peers) / self.eta))
            cutoff = peers[k - 1]
            if not self.objective.better(mine, cutoff) and mine != cutoff:
                return True
        return False


STOPPERS = {"medianstop": MedianStop, "asha": ASHA}


def make_stopper(objective: ObjectiveSpec,
                 spec: Optional[EarlyStoppingSpec]) -> Optional[EarlyStopper]:
    if spec is None or spec.name in ("", "none"):
        return None
    if spec.name not in STOPPERS:
        raise ValueError(f"unknown early stopper {spec.name!r}")
    return STOPPERS[spec.name](objective, spec)
