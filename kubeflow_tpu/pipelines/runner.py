"""Pipeline runner — DAG execution with caching, lineage, retries.

The reference splits this across Argo (DAG walk), the KFP v2 driver (input
resolution + cache check), the launcher (artifact IO + MLMD recording), and
the cache server (SURVEY.md §2.5, §3.4). Here those roles are one runner
with the same behaviors, executing over threads locally; the metadata
backend is pluggable (in-proc store or the native C++ server).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import enum
import hashlib
import inspect
import json
import os
import shutil
import threading
import time
import uuid
from typing import Any, Optional

from kubeflow_tpu.metadata import INPUT, OUTPUT, MetadataStore
from kubeflow_tpu.pipelines import dsl


class TaskState(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SKIPPED = "Skipped"
    CACHED = "Cached"


@dataclasses.dataclass
class TaskResult:
    name: str
    state: TaskState = TaskState.PENDING
    outputs: dict[str, Any] = dataclasses.field(default_factory=dict)
    error: str = ""
    attempts: int = 0
    execution_id: Optional[int] = None


@dataclasses.dataclass
class RunResult:
    run_id: str
    state: TaskState
    tasks: dict[str, TaskResult]
    params: dict[str, Any]
    context_id: Optional[int] = None

    def task(self, name: str) -> TaskResult:
        return self.tasks[name]

    @property
    def succeeded(self) -> bool:
        return self.state == TaskState.SUCCEEDED


class _Skip(Exception):
    pass


class LocalRunner:
    """Executes a traced pipeline graph. ``workdir`` holds artifacts and the
    execution cache; ``metadata`` records lineage."""

    def __init__(self, workdir: str, metadata=None, max_workers: int = 8):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.metadata = metadata if metadata is not None else MetadataStore()
        self.max_workers = max_workers
        self.cache_dir = os.path.join(self.workdir, "_cache")
        os.makedirs(self.cache_dir, exist_ok=True)

    # ------------------------------------------------------------ run ----

    def run(self, pipe: dsl.Pipeline,
            arguments: Optional[dict[str, Any]] = None,
            run_id: Optional[str] = None) -> RunResult:
        args = {k: v for k, v in pipe.spec.params.items() if v is not None}
        args.update(arguments or {})
        missing = [k for k in pipe.spec.params if k not in args]
        if missing:
            raise ValueError(f"missing pipeline arguments: {missing}")

        ctx = pipe.trace()
        run_id = run_id or f"{pipe.name}-{uuid.uuid4().hex[:8]}"
        run_dir = os.path.join(self.workdir, run_id)
        os.makedirs(run_dir, exist_ok=True)
        context_id = self.metadata.put_context(
            "pipeline_run", run_id, properties={"pipeline": pipe.name})

        # expand ParallelFor groups into per-item task instances
        tasks, loop_of = self._expand(ctx, args)

        results = {name: TaskResult(name=name) for name in tasks}
        lock = threading.Lock()
        run_failed = threading.Event()

        main = {n: t for n, t in tasks.items() if not t.is_exit_handler}
        handlers = {n: t for n, t in tasks.items() if t.is_exit_handler}

        self._execute_dag(main, results, args, ctx, run_dir, context_id,
                          lock, run_failed, loop_of)
        # exit handlers always run, even after failure
        self._execute_dag(handlers, results, args, ctx, run_dir, context_id,
                          lock, threading.Event(), loop_of)

        state = TaskState.FAILED if run_failed.is_set() else TaskState.SUCCEEDED
        return RunResult(run_id=run_id, state=state, tasks=results,
                         params=args, context_id=context_id)

    # ------------------------------------------------- loop expansion ----

    def _expand(self, ctx: dsl._PipelineContext, args: dict
                ) -> tuple[dict[str, dsl.Task], dict[str, tuple[str, Any]]]:
        """Fan ParallelFor bodies out per item. Returns (tasks, loop_of)
        where loop_of maps expanded task name -> (loop_id, item)."""
        tasks: dict[str, dsl.Task] = {}
        loop_of: dict[str, tuple[str, Any]] = {}
        loops: dict[str, list[dsl.Task]] = {}
        for t in ctx.tasks.values():
            if t.loop is None:
                tasks[t.name] = t
            else:
                loops.setdefault(t.loop.loop_id, []).append(t)

        # a task OUTSIDE a loop referencing a loop member has no single
        # instance to bind to — needs a dynamic collect step (not yet built);
        # fail at expansion with a clear message instead of a runtime race
        loop_member_names = {m.name for ms in loops.values() for m in ms}
        for t in tasks.values():
            refs = [v for v in t.arguments.values()
                    if isinstance(v, dsl.OutputRef)]
            if t.condition is not None:
                refs += [s for s in (t.condition.lhs, t.condition.rhs)
                         if isinstance(s, dsl.OutputRef)]
            for r in refs:
                if r.task in loop_member_names:
                    raise NotImplementedError(
                        f"task {t.name!r} consumes output of ParallelFor "
                        f"member {r.task!r}; aggregating over a fan-out "
                        f"requires a collect step, which is not supported "
                        f"yet")

        for loop_id, members in loops.items():
            loop = members[0].loop
            items = loop.items
            if isinstance(items, dsl.ParamRef):
                items = args[items.name]
            elif isinstance(items, dsl.OutputRef):
                raise NotImplementedError(
                    "ParallelFor over a task output requires the dynamic "
                    "driver; use a pipeline parameter or static list")
            member_names = {m.name for m in members}
            for i, item in enumerate(items):
                for m in members:
                    inst_name = f"{m.name}[{i}]"
                    inst = dsl.Task(
                        name=inst_name, component=m.component,
                        arguments=dict(m.arguments),
                        dependencies=[
                            # intra-loop deps bind within the iteration
                            (f"{d}[{i}]" if d in member_names else d)
                            for d in m.dependencies
                        ],
                        condition=m.condition, loop=m.loop,
                        is_exit_handler=m.is_exit_handler)
                    tasks[inst_name] = inst
                    loop_of[inst_name] = (loop_id, item)
        return tasks, loop_of

    # ------------------------------------------------------ dag walk ----

    def _execute_dag(self, tasks, results, args, ctx, run_dir, context_id,
                     lock, run_failed, loop_of):
        if not tasks:
            return
        remaining = dict(tasks)
        with concurrent.futures.ThreadPoolExecutor(self.max_workers) as pool:
            futures: dict[concurrent.futures.Future, str] = {}
            while remaining or futures:
                ready = [
                    n for n, t in remaining.items()
                    if all(results[d].state in (TaskState.SUCCEEDED,
                                                TaskState.CACHED,
                                                TaskState.SKIPPED,
                                                TaskState.FAILED)
                           for d in self._deps(t, tasks, ctx, loop_of))
                ]
                for n in ready:
                    t = remaining.pop(n)
                    futures[pool.submit(
                        self._run_task, t, results, args, ctx, run_dir,
                        context_id, lock, run_failed, loop_of)] = n
                if not futures:
                    if remaining:    # dependency cycle or unresolvable
                        for n in remaining:
                            results[n].state = TaskState.SKIPPED
                            results[n].error = "unreachable (cycle?)"
                        run_failed.set()
                    return
                done, _ = concurrent.futures.wait(
                    futures, return_when=concurrent.futures.FIRST_COMPLETED)
                for f in done:
                    futures.pop(f)
                    f.result()       # propagate runner bugs loudly

    def _deps(self, task: dsl.Task, tasks, ctx, loop_of) -> set[str]:
        """Explicit deps + data deps from argument references."""
        deps = set(task.dependencies)
        loop_item = loop_of.get(task.name)
        for v in task.arguments.values():
            if isinstance(v, dsl.OutputRef):
                deps.add(self._ref_instance(v.task, task, tasks, loop_item))
        expr = task.condition
        if expr is not None:
            for side in (expr.lhs, expr.rhs):
                if isinstance(side, dsl.OutputRef):
                    deps.add(self._ref_instance(side.task, task, tasks,
                                                loop_item))
        return {d for d in deps if d in tasks}

    @staticmethod
    def _ref_instance(ref_task: str, task: dsl.Task, tasks,
                      loop_item) -> str:
        """Inside loop iteration i, references to loop members bind to the
        same iteration's instance."""
        if loop_item is not None and task.name.endswith("]"):
            idx = task.name[task.name.rfind("["):]
            if f"{ref_task}{idx}" in tasks:
                return f"{ref_task}{idx}"
        return ref_task

    # ----------------------------------------------------- task exec ----

    def _run_task(self, task, results, args, ctx, run_dir, context_id,
                  lock, run_failed, loop_of):
        result = results[task.name]
        try:
            self._run_task_inner(task, results, args, run_dir, context_id,
                                 lock, run_failed, loop_of, result)
        except _Skip as s:
            result.state = TaskState.SKIPPED
            result.error = str(s)
        except Exception as e:
            result.state = TaskState.FAILED
            result.error = f"{type(e).__name__}: {e}"
            run_failed.set()

    def _run_task_inner(self, task, results, args, run_dir, context_id,
                        lock, run_failed, loop_of, result):
        spec = task.component.spec
        loop_item = loop_of.get(task.name)

        # upstream failure/skip propagation
        for d in self._deps(task, results, None, loop_of):
            if results[d].state in (TaskState.FAILED, TaskState.SKIPPED):
                raise _Skip(f"upstream {d} {results[d].state.value.lower()}")
        if run_failed.is_set() and not task.is_exit_handler:
            raise _Skip("run already failed")

        resolve = lambda v: self._resolve(v, results, args, task, loop_of)
        if task.condition is not None:
            if not self._eval_condition(task.condition, resolve):
                raise _Skip("condition false")

        # resolve inputs
        kwargs: dict[str, Any] = {}
        input_artifacts: dict[str, dsl.Artifact] = {}
        for pname, kind in spec.inputs.items():
            if pname in spec.output_artifacts:
                continue
            if kind == "parameter":
                if pname in task.arguments:
                    kwargs[pname] = resolve(task.arguments[pname])
                elif pname in spec.defaults:
                    kwargs[pname] = spec.defaults[pname]
                else:
                    raise TypeError(
                        f"{task.name}: missing argument {pname!r}")
            else:
                art = resolve(task.arguments[pname])
                if not isinstance(art, dsl.Artifact):
                    raise TypeError(
                        f"{task.name}: input {pname!r} expects an artifact")
                kwargs[pname] = art
                input_artifacts[pname] = art

        # cache check
        fingerprint = self._fingerprint(spec, kwargs, input_artifacts)
        if spec.cache_enabled:
            cached = self._cache_lookup(fingerprint)
            if cached is not None:
                result.outputs = cached
                result.state = TaskState.CACHED
                self._record(task, context_id, kwargs, input_artifacts,
                             cached, "CACHED", result)
                return

        # create output artifacts
        task_dir = os.path.join(run_dir, task.name.replace("/", "_"))
        os.makedirs(task_dir, exist_ok=True)
        for oname, otype in spec.output_artifacts.items():
            cls = dsl.ARTIFACT_TYPES.get(otype, dsl.Artifact)
            kwargs[oname] = cls(
                uri=os.path.join(task_dir, oname), name=oname)

        # execute with retries
        result.state = TaskState.RUNNING
        last_err: Optional[Exception] = None
        for attempt in range(spec.retries + 1):
            result.attempts = attempt + 1
            try:
                ret = spec.fn(**kwargs)
                last_err = None
                break
            except Exception as e:
                last_err = e
        if last_err is not None:
            self._record(task, context_id, kwargs, input_artifacts, {},
                         "FAILED", result)
            raise last_err

        outputs: dict[str, Any] = {
            oname: kwargs[oname] for oname in spec.output_artifacts}
        if spec.return_output:
            outputs["Output"] = ret
        result.outputs = outputs
        result.state = TaskState.SUCCEEDED
        if spec.cache_enabled:
            self._cache_put(fingerprint, outputs)
        self._record(task, context_id, kwargs, input_artifacts, outputs,
                     "COMPLETE", result)

    # ---------------------------------------------------- resolution ----

    def _resolve(self, v, results, args, task, loop_of):
        if isinstance(v, dsl.ParamRef):
            return args[v.name]
        if isinstance(v, dsl.OutputRef):
            inst = self._ref_instance(v.task, task, results,
                                      loop_of.get(task.name))
            dep = results[inst]
            if v.output not in dep.outputs:
                raise KeyError(
                    f"task {inst!r} has no output {v.output!r}")
            return dep.outputs[v.output]
        if isinstance(v, dsl.LoopItemRef):
            loop_item = loop_of.get(task.name)
            if loop_item is None or loop_item[0] != v.loop_id:
                raise RuntimeError(
                    f"{task.name}: loop item reference outside its loop")
            item = loop_item[1]
            return item[v.field] if v.field else item
        return v

    def _eval_condition(self, expr: dsl.ConditionExpr, resolve) -> bool:
        lhs, rhs = resolve(expr.lhs), resolve(expr.rhs)
        return {
            "==": lambda: lhs == rhs,
            "!=": lambda: lhs != rhs,
            ">": lambda: lhs > rhs,
            ">=": lambda: lhs >= rhs,
            "<": lambda: lhs < rhs,
            "<=": lambda: lhs <= rhs,
        }[expr.op]()

    # -------------------------------------------------------- cache ----

    def _fingerprint(self, spec, kwargs, input_artifacts) -> str:
        h = hashlib.sha256()
        h.update(spec.name.encode())
        try:
            h.update(inspect.getsource(spec.fn).encode())
        except OSError:
            h.update(repr(spec.fn).encode())
        for k in sorted(kwargs):
            v = kwargs[k]
            if isinstance(v, dsl.Artifact):
                h.update(f"{k}:artifact:".encode())
                h.update(self._artifact_digest(v))
            else:
                h.update(f"{k}:{json.dumps(v, sort_keys=True, default=repr)}"
                         .encode())
        return h.hexdigest()

    @staticmethod
    def _artifact_digest(art: dsl.Artifact) -> bytes:
        h = hashlib.sha256()
        if os.path.isfile(art.uri):
            with open(art.uri, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        elif os.path.isdir(art.uri):
            for root, _, files in sorted(os.walk(art.uri)):
                for fname in sorted(files):
                    p = os.path.join(root, fname)
                    h.update(fname.encode())
                    with open(p, "rb") as f:
                        h.update(f.read())
        h.update(json.dumps(art.metadata, sort_keys=True).encode())
        return h.digest()

    def _cache_lookup(self, fingerprint: str) -> Optional[dict[str, Any]]:
        entry = os.path.join(self.cache_dir, fingerprint)
        meta_path = os.path.join(entry, "outputs.json")
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        outputs: dict[str, Any] = {}
        for name, rec in meta.items():
            if rec["kind"] == "artifact":
                cls = dsl.ARTIFACT_TYPES.get(rec["type"], dsl.Artifact)
                art = cls(uri=os.path.join(entry, name), name=name)
                art.metadata = rec.get("metadata", {})
                outputs[name] = art
            else:
                outputs[name] = rec["value"]
        return outputs

    def _cache_put(self, fingerprint: str, outputs: dict[str, Any]) -> None:
        entry = os.path.join(self.cache_dir, fingerprint)
        tmp = entry + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        meta: dict[str, Any] = {}
        for name, v in outputs.items():
            if isinstance(v, dsl.Artifact):
                dest = os.path.join(tmp, name)
                if os.path.isdir(v.uri):
                    shutil.copytree(v.uri, dest)
                elif os.path.isfile(v.uri):
                    shutil.copy2(v.uri, dest)
                meta[name] = {"kind": "artifact", "type": type(v).TYPE,
                              "metadata": v.metadata}
            else:
                try:
                    json.dumps(v)
                except TypeError:
                    continue        # unserializable return: don't cache it
                meta[name] = {"kind": "value", "value": v}
        with open(os.path.join(tmp, "outputs.json"), "w") as f:
            json.dump(meta, f)
        shutil.rmtree(entry, ignore_errors=True)
        try:
            os.replace(tmp, entry)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)   # concurrent writer won

    # ----------------------------------------------------- metadata ----

    def _record(self, task, context_id, kwargs, input_artifacts, outputs,
                state, result) -> None:
        spec = task.component.spec
        eid = self.metadata.put_execution(
            type=spec.name, name=task.name, state=state,
            properties={k: v for k, v in kwargs.items()
                        if not isinstance(v, dsl.Artifact)
                        and _jsonable(v)})
        result.execution_id = eid
        self.metadata.associate(context_id, eid)
        for pname, art in input_artifacts.items():
            aid = getattr(art, "_mlmd_id", None)
            if aid is None:
                aid = self.metadata.put_artifact(
                    type=type(art).TYPE, uri=art.uri, name=art.name,
                    properties=art.metadata)
                art._mlmd_id = aid
            self.metadata.put_event(eid, aid, INPUT, path=pname)
        for oname, v in outputs.items():
            if not isinstance(v, dsl.Artifact):
                continue
            aid = self.metadata.put_artifact(
                type=type(v).TYPE, uri=v.uri, name=v.name,
                properties=v.metadata)
            v._mlmd_id = aid
            self.metadata.put_event(eid, aid, OUTPUT, path=oname)
            self.metadata.attribute(context_id, aid)


def _jsonable(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except TypeError:
        return False
