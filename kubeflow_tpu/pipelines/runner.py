"""Pipeline runner — DAG execution with caching, lineage, retries.

The reference splits this across Argo (DAG walk), the KFP v2 driver (input
resolution + cache check), the launcher (artifact IO + MLMD recording), and
the cache server (SURVEY.md §2.5, §3.4). Here those roles are one runner
with the same behaviors, executing over threads locally; the metadata
backend is pluggable (in-proc store or the native C++ server).

Loop expansion: tasks may sit under arbitrarily nested ParallelFor blocks;
instances are the cross product of all enclosing loops, named
``task[i][j]...``. References bind per-iteration: a consumer inside the same
loops reads the same iteration's producer; a consumer OUTSIDE a producer's
loops would need a collect/aggregate step, which is rejected with a clear
error at expansion time.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import enum
import hashlib
import inspect
import itertools
import json
import os
import re
import shutil
import threading
import time
import uuid
from typing import Any, Optional

from kubeflow_tpu.metadata import INPUT, OUTPUT, MetadataStore
from kubeflow_tpu.pipelines import dsl


class TaskState(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SKIPPED = "Skipped"
    CACHED = "Cached"

FINISHED = (TaskState.SUCCEEDED, TaskState.CACHED, TaskState.SKIPPED,
            TaskState.FAILED)


@dataclasses.dataclass
class TaskResult:
    name: str
    state: TaskState = TaskState.PENDING
    outputs: dict[str, Any] = dataclasses.field(default_factory=dict)
    error: str = ""
    attempts: int = 0
    execution_id: Optional[int] = None


@dataclasses.dataclass
class RunResult:
    run_id: str
    state: TaskState
    tasks: dict[str, TaskResult]
    params: dict[str, Any]
    context_id: Optional[int] = None
    error: str = ""               # launch-level failure (task errors live
                                  # on the TaskResults)

    def task(self, name: str) -> TaskResult:
        return self.tasks[name]

    @property
    def succeeded(self) -> bool:
        return self.state == TaskState.SUCCEEDED


class _Skip(Exception):
    pass


@dataclasses.dataclass
class _Instance:
    """One expanded task instance (a concrete loop iteration)."""

    name: str
    task: dsl.Task
    loop_items: dict[str, Any]          # loop_id -> item value
    idx: dict[str, int]                 # loop_id -> iteration index
    base_loops: dict[str, list[str]]    # task base name -> its loop ids
    deps: set[str] = dataclasses.field(default_factory=set)


_RUN_ID_RE = re.compile(r"[A-Za-z0-9][\w.\-]*", re.ASCII)


def validate_run_id(run_id: str) -> None:
    """run_id becomes a directory name under the runner workdir, so
    client-supplied ids (the HTTP run_id field) must not traverse out of
    it, collapse onto it ("."), or collide with reserved entries like
    the leading-underscore cache dir."""
    if not _RUN_ID_RE.fullmatch(run_id):
        raise ValueError(f"invalid run_id {run_id!r}")


def sanitize_run_component(name: str) -> str:
    """Make an arbitrary pipeline/schedule name safe inside an
    auto-generated run_id (strict validation applies only to ids a
    CLIENT supplies; legal-but-odd pipeline names must keep working)."""
    out = re.sub(r"[^\w.\-]", "-", name, flags=re.ASCII)
    if not out or not out[0].isalnum():
        out = "p" + out
    return out


class LocalRunner:
    """Executes a traced pipeline graph. ``workdir`` holds artifacts and the
    execution cache; ``metadata`` records lineage."""

    def __init__(self, workdir: str, metadata=None, max_workers: int = 8):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.metadata = metadata if metadata is not None else MetadataStore()
        self.max_workers = max_workers
        self.cache_dir = os.path.join(self.workdir, "_cache")
        os.makedirs(self.cache_dir, exist_ok=True)

    # ------------------------------------------------------------ run ----

    def run(self, pipe: dsl.Pipeline,
            arguments: Optional[dict[str, Any]] = None,
            run_id: Optional[str] = None) -> RunResult:
        args = {k: v for k, v in pipe.spec.params.items()
                if v is not dsl.REQUIRED}
        args.update(arguments or {})
        missing = [k for k in pipe.spec.params if k not in args]
        if missing:
            raise ValueError(f"missing pipeline arguments: {missing}")

        ctx = pipe.trace()
        if run_id is None:
            run_id = (f"{sanitize_run_component(pipe.name)}-"
                      f"{uuid.uuid4().hex[:8]}")
        else:
            validate_run_id(run_id)
        run_dir = os.path.join(self.workdir, run_id)
        os.makedirs(run_dir, exist_ok=True)
        context_id = self.metadata.put_context(
            "pipeline_run", run_id, properties={"pipeline": pipe.name})
        # cross-process run state (the persistence-agent role): a status
        # execution any other process can read via run_status()
        status_id = self.metadata.put_execution(
            "pipeline_run_status", name=f"{run_id}/status", state="RUNNING",
            properties={"pipeline": pipe.name})
        self.metadata.associate(context_id, status_id)

        # any synchronous failure (expansion errors included) must finalize
        # the status record — a dead run must never read as RUNNING forever
        try:
            instances = self._expand(ctx, args)
            results = {name: TaskResult(name=name) for name in instances}
            run_failed = threading.Event()

            main = {n: i for n, i in instances.items()
                    if not i.task.is_exit_handler}
            handlers = {n: i for n, i in instances.items()
                        if i.task.is_exit_handler}

            self._execute_dag(main, results, args, run_dir, context_id,
                              run_failed)
            # exit handlers always run, even after failure
            self._execute_dag(handlers, results, args, run_dir, context_id,
                              threading.Event())
        except BaseException:
            self.metadata.update_execution(
                status_id, state="FAILED",
                properties={"tasks": {}})
            raise

        state = (TaskState.FAILED if run_failed.is_set()
                 else TaskState.SUCCEEDED)
        self.metadata.update_execution(
            status_id, state=state.value.upper(),
            properties={"tasks": {
                n: r.state.value for n, r in results.items()}})
        return RunResult(run_id=run_id, state=state, tasks=results,
                         params=args, context_id=context_id)

    # ------------------------------------------------- loop expansion ----

    def _expand(self, ctx: dsl._PipelineContext,
                args: dict) -> dict[str, _Instance]:
        base_loops: dict[str, list[str]] = {
            t.name: [lp.loop_id for lp in t.loops]
            for t in ctx.tasks.values()
        }
        # resolve every loop's item list once
        items_of: dict[str, list] = {}
        for t in ctx.tasks.values():
            for lp in t.loops:
                if lp.loop_id in items_of:
                    continue
                items = lp.items
                if isinstance(items, dsl.ParamRef):
                    items = args[items.name]
                elif isinstance(items, (dsl.OutputRef, dsl.LoopItemRef)):
                    raise NotImplementedError(
                        "ParallelFor over a task output requires the dynamic "
                        "driver; use a pipeline parameter or static list")
                items_of[lp.loop_id] = list(items)

        instances: dict[str, _Instance] = {}
        for t in ctx.tasks.values():
            lids = base_loops[t.name]
            ranges = [range(len(items_of[lid])) for lid in lids]
            for combo in itertools.product(*ranges):
                idx = dict(zip(lids, combo))
                name = t.name + "".join(f"[{i}]" for i in combo)
                instances[name] = _Instance(
                    name=name, task=t,
                    loop_items={lid: items_of[lid][i]
                                for lid, i in idx.items()},
                    idx=idx, base_loops=base_loops)

        for inst in instances.values():
            t = inst.task
            targets = set(t.dependencies)
            for v in t.arguments.values():
                if isinstance(v, dsl.OutputRef):
                    targets.add(v.task)
            for c in t.conditions:
                for side in (c.lhs, c.rhs):
                    if isinstance(side, dsl.OutputRef):
                        targets.add(side.task)
            for ref in targets:
                if ref in base_loops:
                    inst.deps.add(self._bind(ref, inst, base_loops))
        return instances

    @staticmethod
    def _bind(ref_base: str, inst: _Instance,
              base_loops: dict[str, list[str]]) -> str:
        """Expanded name of the referenced task's instance as seen from
        ``inst``: every loop of the target must be one of ours."""
        ref_lids = base_loops[ref_base]
        missing = [lid for lid in ref_lids if lid not in inst.idx]
        if missing:
            raise NotImplementedError(
                f"task {inst.task.name!r} consumes output of ParallelFor "
                f"member {ref_base!r}; aggregating over a fan-out requires "
                f"a collect step, which is not supported yet")
        return ref_base + "".join(f"[{inst.idx[lid]}]" for lid in ref_lids)

    # ------------------------------------------------------ dag walk ----

    def _execute_dag(self, instances, results, args, run_dir, context_id,
                     run_failed):
        if not instances:
            return
        remaining = dict(instances)
        with concurrent.futures.ThreadPoolExecutor(self.max_workers) as pool:
            futures: dict[concurrent.futures.Future, str] = {}
            while remaining or futures:
                ready = [
                    n for n, inst in remaining.items()
                    if all(results[d].state in FINISHED
                           for d in inst.deps if d in results)
                ]
                for n in ready:
                    inst = remaining.pop(n)
                    futures[pool.submit(
                        self._run_task, inst, results, args, run_dir,
                        context_id, run_failed)] = n
                if not futures:
                    if remaining:    # dependency cycle or unresolvable
                        for n in remaining:
                            results[n].state = TaskState.SKIPPED
                            results[n].error = "unreachable (cycle?)"
                        run_failed.set()
                    return
                done, _ = concurrent.futures.wait(
                    futures, return_when=concurrent.futures.FIRST_COMPLETED)
                for f in done:
                    futures.pop(f)
                    f.result()       # propagate runner bugs loudly

    # ----------------------------------------------------- task exec ----

    def _run_task(self, inst, results, args, run_dir, context_id,
                  run_failed):
        result = results[inst.name]
        try:
            self._run_task_inner(inst, results, args, run_dir, context_id,
                                 run_failed, result)
        except _Skip as s:
            result.state = TaskState.SKIPPED
            result.error = str(s)
        except Exception as e:
            result.state = TaskState.FAILED
            result.error = f"{type(e).__name__}: {e}"
            run_failed.set()

    def _run_task_inner(self, inst, results, args, run_dir, context_id,
                        run_failed, result):
        task = inst.task
        spec = task.component.spec

        # upstream failure/skip propagation
        for d in inst.deps:
            if d in results and results[d].state in (TaskState.FAILED,
                                                     TaskState.SKIPPED):
                raise _Skip(f"upstream {d} {results[d].state.value.lower()}")
        if run_failed.is_set() and not task.is_exit_handler:
            raise _Skip("run already failed")

        resolve = lambda v: self._resolve(v, results, args, inst)
        for expr in task.conditions:          # ALL nested conditions hold
            if not self._eval_condition(expr, resolve):
                raise _Skip("condition false")

        # resolve inputs
        kwargs: dict[str, Any] = {}
        input_artifacts: dict[str, dsl.Artifact] = {}
        for pname, kind in spec.inputs.items():
            if pname in spec.output_artifacts:
                continue
            if kind == "parameter":
                if pname in task.arguments:
                    kwargs[pname] = resolve(task.arguments[pname])
                elif pname in spec.defaults:
                    kwargs[pname] = spec.defaults[pname]
                else:
                    raise TypeError(
                        f"{inst.name}: missing argument {pname!r}")
            else:
                art = resolve(task.arguments[pname])
                if not isinstance(art, dsl.Artifact):
                    raise TypeError(
                        f"{inst.name}: input {pname!r} expects an artifact")
                kwargs[pname] = art
                input_artifacts[pname] = art

        # cache check
        fingerprint = self._fingerprint(spec, kwargs, input_artifacts)
        if spec.cache_enabled:
            cached = self._cache_lookup(fingerprint)
            if cached is not None:
                result.outputs = cached
                result.state = TaskState.CACHED
                self._record(inst, context_id, kwargs, input_artifacts,
                             cached, "CACHED", result)
                return

        # create output artifacts
        task_dir = os.path.join(run_dir, inst.name.replace("/", "_"))
        os.makedirs(task_dir, exist_ok=True)
        for oname, otype in spec.output_artifacts.items():
            cls = dsl.ARTIFACT_TYPES.get(otype, dsl.Artifact)
            kwargs[oname] = cls(
                uri=os.path.join(task_dir, oname), name=oname)

        # execute with retries
        result.state = TaskState.RUNNING
        last_err: Optional[Exception] = None
        ret = None
        for attempt in range(spec.retries + 1):
            result.attempts = attempt + 1
            try:
                ret = spec.fn(**kwargs)
                last_err = None
                break
            except Exception as e:
                last_err = e
        if last_err is not None:
            self._record(inst, context_id, kwargs, input_artifacts, {},
                         "FAILED", result)
            raise last_err

        outputs: dict[str, Any] = {
            oname: kwargs[oname] for oname in spec.output_artifacts}
        if spec.return_output:
            outputs["Output"] = ret
        result.outputs = outputs
        result.state = TaskState.SUCCEEDED
        if spec.cache_enabled:
            self._cache_put(fingerprint, outputs)
        self._record(inst, context_id, kwargs, input_artifacts, outputs,
                     "COMPLETE", result)

    # ---------------------------------------------------- resolution ----

    def _resolve(self, v, results, args, inst: _Instance):
        if isinstance(v, dsl.ParamRef):
            return args[v.name]
        if isinstance(v, dsl.OutputRef):
            dep_name = self._bind(v.task, inst, inst.base_loops)
            dep = results[dep_name]
            if v.output not in dep.outputs:
                raise KeyError(
                    f"task {dep_name!r} has no output {v.output!r}")
            return dep.outputs[v.output]
        if isinstance(v, dsl.LoopItemRef):
            if v.loop_id not in inst.loop_items:
                raise RuntimeError(
                    f"{inst.name}: loop item reference outside its loop")
            item = inst.loop_items[v.loop_id]
            return item[v.field] if v.field else item
        return v

    def _eval_condition(self, expr: dsl.ConditionExpr, resolve) -> bool:
        lhs, rhs = resolve(expr.lhs), resolve(expr.rhs)
        return {
            "==": lambda: lhs == rhs,
            "!=": lambda: lhs != rhs,
            ">": lambda: lhs > rhs,
            ">=": lambda: lhs >= rhs,
            "<": lambda: lhs < rhs,
            "<=": lambda: lhs <= rhs,
        }[expr.op]()

    # -------------------------------------------------------- cache ----

    def _fingerprint(self, spec, kwargs, input_artifacts) -> str:
        h = hashlib.sha256()
        h.update(spec.name.encode())
        try:
            h.update(inspect.getsource(spec.fn).encode())
        except OSError:
            h.update(repr(spec.fn).encode())
        for k in sorted(kwargs):
            v = kwargs[k]
            if isinstance(v, dsl.Artifact):
                h.update(f"{k}:artifact:".encode())
                h.update(self._artifact_digest(v))
            else:
                h.update(f"{k}:{json.dumps(v, sort_keys=True, default=repr)}"
                         .encode())
        return h.hexdigest()

    @staticmethod
    def _artifact_digest(art: dsl.Artifact) -> bytes:
        h = hashlib.sha256()
        if os.path.isfile(art.uri):
            with open(art.uri, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        elif os.path.isdir(art.uri):
            for root, _, files in sorted(os.walk(art.uri)):
                for fname in sorted(files):
                    p = os.path.join(root, fname)
                    # hash the path RELATIVE to the artifact root, so the
                    # same bytes under a different layout digest differently
                    h.update(os.path.relpath(p, art.uri).encode())
                    with open(p, "rb") as f:
                        h.update(f.read())
        h.update(json.dumps(art.metadata, sort_keys=True).encode())
        return h.digest()

    def _cache_lookup(self, fingerprint: str) -> Optional[dict[str, Any]]:
        entry = os.path.join(self.cache_dir, fingerprint)
        meta_path = os.path.join(entry, "outputs.json")
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        outputs: dict[str, Any] = {}
        for name, rec in meta.items():
            if rec["kind"] == "artifact":
                cls = dsl.ARTIFACT_TYPES.get(rec["type"], dsl.Artifact)
                art = cls(uri=os.path.join(entry, name), name=name)
                art.metadata = rec.get("metadata", {})
                outputs[name] = art
            else:
                outputs[name] = rec["value"]
        return outputs

    def _cache_put(self, fingerprint: str, outputs: dict[str, Any]) -> None:
        # all-or-nothing: a partial entry (e.g. missing an unserializable
        # return value) would poison every future cache hit
        for v in outputs.values():
            if not isinstance(v, dsl.Artifact) and not _jsonable(v):
                return
        entry = os.path.join(self.cache_dir, fingerprint)
        tmp = entry + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        meta: dict[str, Any] = {}
        for name, v in outputs.items():
            if isinstance(v, dsl.Artifact):
                dest = os.path.join(tmp, name)
                if os.path.isdir(v.uri):
                    shutil.copytree(v.uri, dest)
                elif os.path.isfile(v.uri):
                    shutil.copy2(v.uri, dest)
                meta[name] = {"kind": "artifact", "type": type(v).TYPE,
                              "metadata": v.metadata}
            else:
                meta[name] = {"kind": "value", "value": v}
        with open(os.path.join(tmp, "outputs.json"), "w") as f:
            json.dump(meta, f)
        shutil.rmtree(entry, ignore_errors=True)
        try:
            os.replace(tmp, entry)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)   # concurrent writer won

    # ----------------------------------------------------- metadata ----

    def _record(self, inst, context_id, kwargs, input_artifacts, outputs,
                state, result) -> None:
        spec = inst.task.component.spec
        eid = self.metadata.put_execution(
            type=spec.name, name=inst.name, state=state,
            properties={k: v for k, v in kwargs.items()
                        if not isinstance(v, dsl.Artifact)
                        and _jsonable(v)})
        result.execution_id = eid
        self.metadata.associate(context_id, eid)
        for pname, art in input_artifacts.items():
            aid = getattr(art, "_mlmd_id", None)
            if aid is None:
                aid = self.metadata.put_artifact(
                    type=type(art).TYPE, uri=art.uri, name=art.name,
                    properties=art.metadata)
                art._mlmd_id = aid
            self.metadata.put_event(eid, aid, INPUT, path=pname)
        for oname, v in outputs.items():
            if not isinstance(v, dsl.Artifact):
                continue
            aid = self.metadata.put_artifact(
                type=type(v).TYPE, uri=v.uri, name=v.name,
                properties=v.metadata)
            v._mlmd_id = aid
            self.metadata.put_event(eid, aid, OUTPUT, path=oname)
            self.metadata.attribute(context_id, aid)


def run_status(metadata, run_id: str) -> Optional[dict]:
    """Read a run's persisted state from ANY process holding the metadata
    backend (in-proc WAL replay or the native server) — the reference's
    persistence-agent role: run state outlives the runner process."""
    ctx = metadata.context_by_name("pipeline_run", run_id)
    if ctx is None:
        return None
    for ex in metadata.executions_in_context(ctx.id):
        if ex.type == "pipeline_run_status":
            return {
                "run_id": run_id,
                "pipeline": ex.properties.get("pipeline", ""),
                "state": ex.state,
                "tasks": ex.properties.get("tasks", {}),
                "error": ex.properties.get("error", ""),
            }
    return None


def _jsonable(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except TypeError:
        return False
