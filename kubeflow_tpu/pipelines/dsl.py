"""Pipelines DSL — the kfp.dsl equivalent (SURVEY.md §2.5: @dsl.component,
@dsl.pipeline, Condition/ParallelFor/ExitHandler, artifact types).

Authoring model is the same as the reference: calling a @component inside a
@pipeline function doesn't execute it — it records a Task in the active
pipeline graph; the compiler then lowers the graph to IR. Artifacts pass by
file path (Input[X]/Output[X] annotations), parameters pass by value.
"""

from __future__ import annotations

import dataclasses
import inspect
import typing
from typing import Any, Callable, Generic, Optional, TypeVar


# ------------------------------------------------------------- artifacts ----

class Artifact:
    """Base artifact: a named, typed file/directory plus metadata."""

    TYPE = "system.Artifact"

    def __init__(self, uri: str = "", name: str = ""):
        self.uri = uri
        self.name = name
        self.metadata: dict[str, Any] = {}

    @property
    def path(self) -> str:
        return self.uri


class Dataset(Artifact):
    TYPE = "system.Dataset"


class Model(Artifact):
    TYPE = "system.Model"


class Metrics(Artifact):
    TYPE = "system.Metrics"

    def log_metric(self, name: str, value: float) -> None:
        self.metadata[name] = float(value)


ARTIFACT_TYPES = {c.TYPE: c for c in (Artifact, Dataset, Model, Metrics)}

T = TypeVar("T", bound=Artifact)


class Input(Generic[T]):
    """Annotation marker: ``x: Input[Dataset]``."""


class Output(Generic[T]):
    """Annotation marker: ``x: Output[Model]``."""


def _annotation_kind(ann: Any) -> tuple[str, Optional[type]]:
    """Classify a parameter annotation: ('input_artifact', Dataset),
    ('output_artifact', Model) or ('parameter', None)."""
    origin = typing.get_origin(ann)
    if origin in (Input, Output):
        (art,) = typing.get_args(ann)
        kind = "input_artifact" if origin is Input else "output_artifact"
        return kind, art
    return "parameter", None


# ------------------------------------------------------------ components ----

@dataclasses.dataclass
class ComponentSpec:
    name: str
    fn: Callable
    inputs: dict[str, str]            # param name -> 'parameter'|artifact TYPE
    output_artifacts: dict[str, str]  # param name -> artifact TYPE
    return_output: bool               # fn returns a value => 'Output' param
    defaults: dict[str, Any]
    retries: int = 0
    cache_enabled: bool = True


class Component:
    """A wrapped component function. Calling it inside a pipeline context
    records a Task; calling it outside raises (use .execute for direct
    invocation in tests)."""

    def __init__(self, spec: ComponentSpec):
        self.spec = spec
        self.name = spec.name

    def __call__(self, **kwargs: Any) -> "Task":
        ctx = _PipelineContext.current()
        if ctx is None:
            raise RuntimeError(
                f"component {self.name!r} called outside a pipeline; "
                f"use {self.name}.spec.fn(...) to run the raw function")
        return ctx.add_task(self, kwargs)

    def set_retries(self, retries: int) -> "Component":
        self.spec.retries = retries
        return self

    def set_caching(self, enabled: bool) -> "Component":
        self.spec.cache_enabled = enabled
        return self


def component(fn: Optional[Callable] = None, *, name: Optional[str] = None,
              retries: int = 0, cache: bool = True):
    """Decorator turning a python function into a pipeline component."""

    def wrap(f: Callable) -> Component:
        hints = typing.get_type_hints(f, include_extras=True)
        sig = inspect.signature(f)
        inputs: dict[str, str] = {}
        output_artifacts: dict[str, str] = {}
        defaults: dict[str, Any] = {}
        for pname, p in sig.parameters.items():
            ann = hints.get(pname, Any)
            kind, art = _annotation_kind(ann)
            if kind == "input_artifact":
                inputs[pname] = art.TYPE
            elif kind == "output_artifact":
                output_artifacts[pname] = art.TYPE
            else:
                inputs[pname] = "parameter"
                if p.default is not inspect.Parameter.empty:
                    defaults[pname] = p.default
        # `-> None` means no output (get_type_hints maps it to NoneType)
        returns = hints.get("return", None) not in (None, type(None))
        spec = ComponentSpec(
            name=name or f.__name__, fn=f, inputs=inputs,
            output_artifacts=output_artifacts, return_output=returns,
            defaults=defaults, retries=retries, cache_enabled=cache)
        return Component(spec)

    return wrap(fn) if fn is not None else wrap


# ----------------------------------------------------------- references ----

@dataclasses.dataclass(frozen=True)
class OutputRef:
    """Reference to a task's named output, usable as another task's input
    or in a Condition."""

    task: str
    output: str                     # 'Output' for the return value

    def __eq__(self, other):        # builds a ConditionExpr, not a bool
        return ConditionExpr(self, "==", other)

    def __ne__(self, other):
        return ConditionExpr(self, "!=", other)

    def __gt__(self, other):
        return ConditionExpr(self, ">", other)

    def __ge__(self, other):
        return ConditionExpr(self, ">=", other)

    def __lt__(self, other):
        return ConditionExpr(self, "<", other)

    def __le__(self, other):
        return ConditionExpr(self, "<=", other)

    def __hash__(self):
        return hash((self.task, self.output))


@dataclasses.dataclass(frozen=True)
class ParamRef:
    """Reference to a pipeline-level input parameter."""

    name: str

    def __eq__(self, other):
        return ConditionExpr(self, "==", other)

    def __ne__(self, other):
        return ConditionExpr(self, "!=", other)

    def __gt__(self, other):
        return ConditionExpr(self, ">", other)

    def __ge__(self, other):
        return ConditionExpr(self, ">=", other)

    def __lt__(self, other):
        return ConditionExpr(self, "<", other)

    def __le__(self, other):
        return ConditionExpr(self, "<=", other)

    def __hash__(self):
        return hash(self.name)


@dataclasses.dataclass(frozen=True)
class LoopItemRef:
    """The current item inside a ParallelFor body (or a field of it)."""

    loop_id: str
    field: Optional[str] = None

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return LoopItemRef(self.loop_id, item)


@dataclasses.dataclass(frozen=True)
class ConditionExpr:
    lhs: Any
    op: str
    rhs: Any


# ---------------------------------------------------------------- tasks ----

@dataclasses.dataclass
class Task:
    name: str
    component: Component
    arguments: dict[str, Any]
    dependencies: list[str] = dataclasses.field(default_factory=list)
    # ALL enclosing conditions (outermost first) — every one must hold
    conditions: list[ConditionExpr] = dataclasses.field(default_factory=list)
    # ALL enclosing loops (outermost first) — expansion is their product
    loops: list["ParallelFor"] = dataclasses.field(default_factory=list)
    is_exit_handler: bool = False

    @property
    def output(self) -> OutputRef:
        if not self.component.spec.return_output:
            raise AttributeError(
                f"component {self.component.name!r} has no return value")
        return OutputRef(self.name, "Output")

    @property
    def outputs(self) -> dict[str, OutputRef]:
        refs = {k: OutputRef(self.name, k)
                for k in self.component.spec.output_artifacts}
        if self.component.spec.return_output:
            refs["Output"] = OutputRef(self.name, "Output")
        return refs

    def after(self, *tasks: "Task") -> "Task":
        self.dependencies.extend(t.name for t in tasks)
        return self


# --------------------------------------------------------- control flow ----

class _PipelineContext:
    _stack: list["_PipelineContext"] = []

    def __init__(self, name: str, params: dict[str, Any]):
        self.name = name
        self.params = params
        self.tasks: dict[str, Task] = {}
        self._cond_stack: list[ConditionExpr] = []
        self._loop_stack: list[ParallelFor] = []
        self._exit_stack: list[str] = []   # exit-handler task names
        self._names: dict[str, int] = {}

    @classmethod
    def current(cls) -> Optional["_PipelineContext"]:
        return cls._stack[-1] if cls._stack else None

    def __enter__(self):
        _PipelineContext._stack.append(self)
        return self

    def __exit__(self, *exc):
        _PipelineContext._stack.pop()

    def add_task(self, comp: Component, args: dict[str, Any]) -> Task:
        n = self._names.get(comp.name, 0)
        self._names[comp.name] = n + 1
        tname = comp.name if n == 0 else f"{comp.name}-{n + 1}"
        task = Task(name=tname, component=comp, arguments=dict(args),
                    conditions=list(self._cond_stack),
                    loops=list(self._loop_stack))
        self.tasks[tname] = task
        return task


class Condition:
    """``with Condition(task.output > 0.9):`` — tasks inside run only when
    the expression holds at runtime."""

    def __init__(self, expr: ConditionExpr):
        if not isinstance(expr, ConditionExpr):
            raise TypeError(
                "Condition needs an expression built from a task output or "
                "pipeline parameter (e.g. t.output > 0.5)")
        self.expr = expr

    def __enter__(self):
        ctx = _PipelineContext.current()
        if ctx is None:
            raise RuntimeError("Condition used outside a pipeline")
        ctx._cond_stack.append(self.expr)
        return self

    def __exit__(self, *exc):
        _PipelineContext.current()._cond_stack.pop()


class ParallelFor:
    """``with ParallelFor(items) as item:`` — the body fans out per item at
    runtime. ``items`` is a static list or an upstream output reference."""

    _ids = 0

    def __init__(self, items: Any):
        ParallelFor._ids += 1
        self.loop_id = f"loop-{ParallelFor._ids}"
        self.items = items

    def __enter__(self) -> LoopItemRef:
        ctx = _PipelineContext.current()
        if ctx is None:
            raise RuntimeError("ParallelFor used outside a pipeline")
        ctx._loop_stack.append(self)
        return LoopItemRef(self.loop_id)

    def __exit__(self, *exc):
        _PipelineContext.current()._loop_stack.pop()


class ExitHandler:
    """``with ExitHandler(cleanup_task):`` — the handler task runs at
    pipeline end regardless of failure (the reference's Argo exit handler)."""

    def __init__(self, handler: Task):
        self.handler = handler
        handler.is_exit_handler = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


# ------------------------------------------------------------- pipeline ----

class _Required:
    """Sentinel: pipeline parameter with no default (None IS a valid
    default)."""

    def __repr__(self):
        return "<required>"


REQUIRED = _Required()


@dataclasses.dataclass
class PipelineSpec:
    name: str
    fn: Callable
    params: dict[str, Any]            # name -> default | REQUIRED


class Pipeline:
    def __init__(self, spec: PipelineSpec):
        self.spec = spec
        self.name = spec.name

    def trace(self, arguments: Optional[dict[str, Any]] = None
              ) -> _PipelineContext:
        """Execute the pipeline function to build the task graph. Pipeline
        parameters become ParamRefs so the graph stays symbolic."""
        args = dict(self.spec.params)
        args.update(arguments or {})
        ctx = _PipelineContext(self.name, args)
        with ctx:
            self.spec.fn(**{k: ParamRef(k) for k in self.spec.params})
        return ctx


def pipeline(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    def wrap(f: Callable) -> Pipeline:
        sig = inspect.signature(f)
        params = {}
        for pname, p in sig.parameters.items():
            params[pname] = (REQUIRED if p.default is inspect.Parameter.empty
                             else p.default)
        return Pipeline(PipelineSpec(name=name or f.__name__, fn=f,
                                     params=params))

    return wrap(fn) if fn is not None else wrap
