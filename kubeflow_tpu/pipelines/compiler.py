"""Pipeline compiler — DSL graph → IR document (YAML).

The kfp Compiler equivalent (SURVEY.md §2.5: 'Python DSL ... compiler → IR =
PipelineSpec proto'; §4.4 golden-file tests are the test pattern). The IR is
a plain YAML document (no proto toolchain here) with the same information
content: components, dag tasks, parameter/artifact wiring, trigger
conditions, iterators, exit handlers.
"""

from __future__ import annotations

from typing import Any

import yaml

from kubeflow_tpu.pipelines import dsl

IR_SCHEMA_VERSION = "kubeflow-tpu-ir/v1"


def _encode_value(v: Any) -> dict:
    if isinstance(v, dsl.OutputRef):
        return {"taskOutput": {"task": v.task, "output": v.output}}
    if isinstance(v, dsl.ParamRef):
        return {"pipelineParameter": v.name}
    if isinstance(v, dsl.LoopItemRef):
        d: dict[str, Any] = {"loopItem": v.loop_id}
        if v.field:
            d["field"] = v.field
        return d
    if isinstance(v, dsl.Task):
        raise TypeError(
            f"task {v.name!r} passed directly as an argument; pass "
            f"task.output or task.outputs['name']")
    return {"constant": v}


def _encode_condition(expr: dsl.ConditionExpr) -> dict:
    return {"lhs": _encode_value(expr.lhs), "op": expr.op,
            "rhs": _encode_value(expr.rhs)}


def compile_pipeline(pipe: dsl.Pipeline) -> dict:
    """Lower a pipeline to its IR dict (trace with symbolic parameters)."""
    ctx = pipe.trace()
    components: dict[str, dict] = {}
    tasks: dict[str, dict] = {}

    for task in ctx.tasks.values():
        spec = task.component.spec
        comp_key = f"comp-{spec.name}"
        if comp_key not in components:
            components[comp_key] = {
                "name": spec.name,
                "inputs": dict(spec.inputs),
                "outputArtifacts": dict(spec.output_artifacts),
                "returnOutput": spec.return_output,
                "retries": spec.retries,
                "cacheEnabled": spec.cache_enabled,
                "fnRef": f"{spec.fn.__module__}:{spec.fn.__qualname__}",
            }
        t: dict[str, Any] = {
            "componentRef": comp_key,
            "inputs": {k: _encode_value(v)
                       for k, v in sorted(task.arguments.items())},
        }
        deps = sorted(set(task.dependencies))
        if deps:
            t["dependentTasks"] = deps
        if task.conditions:
            t["triggerConditions"] = [
                _encode_condition(c) for c in task.conditions]
        if task.loops:
            t["iterators"] = [
                {"loopId": lp.loop_id, "items": _encode_value(lp.items)}
                for lp in task.loops
            ]
        if task.is_exit_handler:
            t["exitHandler"] = True
        tasks[task.name] = t

    return {
        "schemaVersion": IR_SCHEMA_VERSION,
        "pipelineInfo": {"name": pipe.name},
        "root": {
            "inputDefinitions": {
                "parameters": {
                    k: ({} if v is dsl.REQUIRED else {"defaultValue": v})
                    for k, v in pipe.spec.params.items()
                }
            },
            "dag": {"tasks": tasks},
        },
        "components": components,
    }


class Compiler:
    """kfp-compatible surface: Compiler().compile(pipeline, path)."""

    def compile(self, pipe: dsl.Pipeline, package_path: str) -> dict:
        ir = compile_pipeline(pipe)
        with open(package_path, "w") as f:
            yaml.safe_dump(ir, f, sort_keys=True)
        return ir


def load_ir(path: str) -> dict:
    with open(path) as f:
        ir = yaml.safe_load(f)
    if ir.get("schemaVersion") != IR_SCHEMA_VERSION:
        raise ValueError(
            f"unsupported IR schema {ir.get('schemaVersion')!r}")
    return ir
