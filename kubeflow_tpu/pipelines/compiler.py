"""Pipeline compiler — DSL graph → IR document (YAML).

The kfp Compiler equivalent (SURVEY.md §2.5: 'Python DSL ... compiler → IR =
PipelineSpec proto'; §4.4 golden-file tests are the test pattern). The IR is
a plain YAML document (no proto toolchain here) with the same information
content: components, dag tasks, parameter/artifact wiring, trigger
conditions, iterators, exit handlers.
"""

from __future__ import annotations

from typing import Any

import yaml

from kubeflow_tpu.pipelines import dsl

IR_SCHEMA_VERSION = "kubeflow-tpu-ir/v1"

# Module prefixes an IR fnRef may trigger an import of. Anything else must
# already be imported by the hosting process — importing an arbitrary
# attacker-named module would execute its top-level code as a side effect,
# even though _resolve_fn later rejects non-Component targets.
_COMPONENT_MODULE_PREFIXES: set[str] = {"kubeflow_tpu"}


def allow_component_modules(*prefixes: str) -> None:
    """Whitelist additional module prefixes for IR fnRef resolution."""
    _COMPONENT_MODULE_PREFIXES.update(prefixes)


def _module_allowed(mod_name: str) -> bool:
    import sys
    if mod_name in sys.modules:
        return True
    return any(mod_name == p or mod_name.startswith(p + ".")
               for p in _COMPONENT_MODULE_PREFIXES)


def _encode_value(v: Any) -> dict:
    if isinstance(v, dsl.OutputRef):
        return {"taskOutput": {"task": v.task, "output": v.output}}
    if isinstance(v, dsl.ParamRef):
        return {"pipelineParameter": v.name}
    if isinstance(v, dsl.LoopItemRef):
        d: dict[str, Any] = {"loopItem": v.loop_id}
        if v.field:
            d["field"] = v.field
        return d
    if isinstance(v, dsl.Task):
        raise TypeError(
            f"task {v.name!r} passed directly as an argument; pass "
            f"task.output or task.outputs['name']")
    return {"constant": v}


def _encode_condition(expr: dsl.ConditionExpr) -> dict:
    return {"lhs": _encode_value(expr.lhs), "op": expr.op,
            "rhs": _encode_value(expr.rhs)}


def compile_pipeline(pipe: dsl.Pipeline) -> dict:
    """Lower a pipeline to its IR dict (trace with symbolic parameters)."""
    ctx = pipe.trace()
    components: dict[str, dict] = {}
    tasks: dict[str, dict] = {}

    for task in ctx.tasks.values():
        spec = task.component.spec
        comp_key = f"comp-{spec.name}"
        if comp_key not in components:
            components[comp_key] = {
                "name": spec.name,
                "inputs": dict(spec.inputs),
                "outputArtifacts": dict(spec.output_artifacts),
                "returnOutput": spec.return_output,
                "retries": spec.retries,
                "cacheEnabled": spec.cache_enabled,
                "fnRef": f"{spec.fn.__module__}:{spec.fn.__qualname__}",
            }
            if spec.defaults:
                # call sites may omit defaulted params; the runner falls
                # back to these at execution time
                components[comp_key]["defaults"] = dict(spec.defaults)
        t: dict[str, Any] = {
            "componentRef": comp_key,
            "inputs": {k: _encode_value(v)
                       for k, v in sorted(task.arguments.items())},
        }
        deps = sorted(set(task.dependencies))
        if deps:
            t["dependentTasks"] = deps
        if task.conditions:
            t["triggerConditions"] = [
                _encode_condition(c) for c in task.conditions]
        if task.loops:
            t["iterators"] = [
                {"loopId": lp.loop_id, "items": _encode_value(lp.items)}
                for lp in task.loops
            ]
        if task.is_exit_handler:
            t["exitHandler"] = True
        tasks[task.name] = t

    return {
        "schemaVersion": IR_SCHEMA_VERSION,
        "pipelineInfo": {"name": pipe.name},
        "root": {
            "inputDefinitions": {
                "parameters": {
                    k: ({} if v is dsl.REQUIRED else {"defaultValue": v})
                    for k, v in pipe.spec.params.items()
                }
            },
            "dag": {"tasks": tasks},
        },
        "components": components,
    }


class Compiler:
    """kfp-compatible surface: Compiler().compile(pipeline, path)."""

    def compile(self, pipe: dsl.Pipeline, package_path: str) -> dict:
        ir = compile_pipeline(pipe)
        with open(package_path, "w") as f:
            yaml.safe_dump(ir, f, sort_keys=True)
        return ir


def load_ir(path: str) -> dict:
    with open(path) as f:
        ir = yaml.safe_load(f)
    if ir.get("schemaVersion") != IR_SCHEMA_VERSION:
        raise ValueError(
            f"unsupported IR schema {ir.get('schemaVersion')!r}")
    return ir


# ---------------------------------------------------------------------------
# IR -> executable pipeline (the API-server side of the compiler: a client
# compiles locally and POSTS the IR; the server re-materializes the graph
# and runs it. Reference analogue: apiserver expanding PipelineSpec proto
# into an Argo Workflow, SURVEY.md §3.4 — here the IR becomes a Pipeline
# whose trace() rebuilds the task graph directly, no user fn re-executed.)
# ---------------------------------------------------------------------------

def _decode_value(d: dict) -> Any:
    if "taskOutput" in d:
        return dsl.OutputRef(d["taskOutput"]["task"], d["taskOutput"]["output"])
    if "pipelineParameter" in d:
        return dsl.ParamRef(d["pipelineParameter"])
    if "loopItem" in d:
        return dsl.LoopItemRef(d["loopItem"], d.get("field"))
    return d["constant"]


def _resolve_fn(fn_ref: str):
    """'module:qualname' -> the raw component function. The module-level
    name is rebound to the Component wrapper by the decorator; resolution
    REQUIRES that wrapper: an IR may only reference functions their owner
    explicitly registered as components. Resolving arbitrary callables
    (e.g. ``os:system``) would turn the IR-upload API into remote code
    execution with attacker-chosen arguments. '<locals>' qualnames
    (components defined inside functions) are not importable by design."""
    import importlib

    mod_name, _, qual = fn_ref.partition(":")
    if "<locals>" in qual:
        raise ValueError(
            f"component fn {fn_ref!r} is not importable (defined inside a "
            "function); IR-submitted pipelines need module-level components")
    if not _module_allowed(mod_name):
        raise ValueError(
            f"component module {mod_name!r} is neither already imported nor "
            "under an allowed prefix (see allow_component_modules); "
            "refusing to import it on behalf of an uploaded IR")
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    if not isinstance(obj, dsl.Component):
        raise ValueError(
            f"{fn_ref!r} is not a registered @dsl.component; IR pipelines "
            "may only call functions their module exposes as components")
    return obj.spec.fn


class _IRPipeline(dsl.Pipeline):
    """A Pipeline whose trace() replays the IR's DAG instead of calling a
    pipeline function."""

    def __init__(self, ir: dict):
        self._ir = ir
        params = {
            k: (v["defaultValue"] if "defaultValue" in v else dsl.REQUIRED)
            for k, v in ir["root"]["inputDefinitions"]["parameters"].items()
        }
        self._components: dict[str, dsl.Component] = {}
        for key, c in ir["components"].items():
            spec = dsl.ComponentSpec(
                name=c["name"], fn=_resolve_fn(c["fnRef"]),
                inputs=dict(c["inputs"]),
                output_artifacts=dict(c["outputArtifacts"]),
                return_output=c["returnOutput"],
                defaults=dict(c.get("defaults", {})),
                retries=c.get("retries", 0),
                cache_enabled=c.get("cacheEnabled", True))
            self._components[key] = dsl.Component(spec)
        super().__init__(dsl.PipelineSpec(
            name=ir["pipelineInfo"]["name"], fn=self._no_fn, params=params))

    @staticmethod
    def _no_fn(**kwargs):
        raise RuntimeError("IR pipelines trace from the document")

    def trace(self, arguments: Optional[dict] = None) -> dsl._PipelineContext:
        args = dict(self.spec.params)
        args.update(arguments or {})
        ctx = dsl._PipelineContext(self.name, args)
        loops: dict[str, dsl.ParallelFor] = {}

        def loop_for(lid: str, items: Any) -> dsl.ParallelFor:
            if lid not in loops:
                lp = dsl.ParallelFor.__new__(dsl.ParallelFor)
                lp.loop_id = lid
                lp.items = items
                loops[lid] = lp
            return loops[lid]

        for tname, t in self._ir["root"]["dag"]["tasks"].items():
            ctx.tasks[tname] = dsl.Task(
                name=tname,
                component=self._components[t["componentRef"]],
                arguments={k: _decode_value(v)
                           for k, v in t.get("inputs", {}).items()},
                dependencies=list(t.get("dependentTasks", [])),
                conditions=[
                    dsl.ConditionExpr(_decode_value(c["lhs"]), c["op"],
                                      _decode_value(c["rhs"]))
                    for c in t.get("triggerConditions", [])],
                loops=[loop_for(it["loopId"], _decode_value(it["items"]))
                       for it in t.get("iterators", [])],
                is_exit_handler=t.get("exitHandler", False),
            )
        return ctx


def pipeline_from_ir(ir: dict) -> dsl.Pipeline:
    """Re-materialize an executable Pipeline from a compiled IR document."""
    if ir.get("schemaVersion") != IR_SCHEMA_VERSION:
        raise ValueError(
            f"unsupported IR schema {ir.get('schemaVersion')!r}")
    return _IRPipeline(ir)
