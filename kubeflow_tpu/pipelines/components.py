"""Launcher components — pipeline tasks that drive the platform's own APIs.

The reference's KFP "launcher component" pattern (SURVEY.md §3.4: the
target config is a pipeline task that *submits a training CR and waits*,
BASELINE.md milestone #5). These are module-level ``@dsl.component``
functions, so IR-submitted pipelines can reference them by fnRef — a
pipeline POSTed to the operator can launch training jobs and HPO sweeps
on that same operator.

Connection comes from the ``operator_url`` argument or the
``KFT_OPERATOR_URL`` env the pipeline pod carries; ``KFT_OPERATOR_TOKEN``
adds a bearer token when the API runs with auth.
"""

from __future__ import annotations

from kubeflow_tpu.pipelines import dsl


def _api(base: str, path: str, payload: bytes | None = None,
         method: str = "GET") -> dict:
    import json
    import os
    import urllib.request

    req = urllib.request.Request(base + path, data=payload, method=method)
    token = os.environ.get("KFT_OPERATOR_TOKEN")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read().decode() or "{}")


def _base(operator_url: str) -> str:
    import os

    base = operator_url or os.environ.get("KFT_OPERATOR_URL", "")
    if not base:
        raise ValueError(
            "no operator endpoint: pass operator_url or set KFT_OPERATOR_URL")
    return base.rstrip("/")


@dsl.component(name="run-training-job", cache=False)
def run_training_job(job_yaml: str, operator_url: str = "",
                     namespace: str = "",
                     timeout_s: float = 600.0,
                     poll_s: float = 0.5) -> dict:
    """Submit a job spec (YAML) to the operator and wait for completion.

    Returns the final job document on success; raises on Failed/timeout so
    the task's retry policy and the run state see the failure. Caching is
    off: submitting a training job is an effect, not a pure function."""
    import time

    from kubeflow_tpu.api.types import from_yaml

    import urllib.error

    base = _base(operator_url)
    spec = from_yaml(job_yaml)
    ns = namespace or spec.namespace or "default"
    try:
        _api(base, f"/apis/v1/namespaces/{ns}/jobs",
             payload=job_yaml.encode(), method="POST")
    except urllib.error.HTTPError as e:
        # idempotent retries: on a name collision from an earlier attempt,
        # delete a terminally-FAILED leftover and resubmit; a live or
        # succeeded one is simply polled (submit-once semantics)
        if b"already exists" not in e.read():
            raise
        doc = _api(base, f"/apis/v1/namespaces/{ns}/jobs/{spec.name}")
        if doc.get("condition") == "Failed":
            _api(base, f"/apis/v1/namespaces/{ns}/jobs/{spec.name}",
                 method="DELETE")
            _api(base, f"/apis/v1/namespaces/{ns}/jobs",
                 payload=job_yaml.encode(), method="POST")
    deadline = time.time() + timeout_s
    doc: dict = {}
    while time.time() < deadline:
        doc = _api(base, f"/apis/v1/namespaces/{ns}/jobs/{spec.name}")
        if doc.get("condition") in ("Succeeded", "Failed"):
            break
        time.sleep(poll_s)
    if doc.get("condition") != "Succeeded":
        raise RuntimeError(
            f"job {ns}/{spec.name} did not succeed: "
            f"condition={doc.get('condition')!r} "
            f"restarts={doc.get('restart_count')}")
    return doc


@dsl.component(name="run-experiment", cache=False)
def run_experiment(experiment: dict, trial_template: str,
                   operator_url: str = "", namespace: str = "",
                   timeout_s: float = 900.0, poll_s: float = 0.5) -> dict:
    """Submit an HPO experiment (spec dict + trial-template YAML) and wait
    for it to finish. Returns the final experiment document (including
    best_trial); raises when the sweep fails."""
    import json
    import time

    import urllib.error

    base = _base(operator_url)
    ns = namespace or experiment.get("namespace") or "default"
    name = experiment["name"]
    try:
        _api(base, f"/apis/v1/namespaces/{ns}/experiments",
             payload=json.dumps({"experiment": experiment,
                                 "trial_template": trial_template}).encode(),
             method="POST")
    except urllib.error.HTTPError as e:
        # retry after a partial earlier attempt: the sweep is resumable,
        # so an existing experiment is polled rather than resubmitted
        if b"already exists" not in e.read():
            raise
    deadline = time.time() + timeout_s
    doc: dict = {}
    while time.time() < deadline:
        doc = _api(base, f"/apis/v1/namespaces/{ns}/experiments/{name}")
        if doc.get("succeeded") or doc.get("failed"):
            break
        time.sleep(poll_s)
    if not doc.get("succeeded"):
        raise RuntimeError(
            f"experiment {ns}/{name} did not succeed: "
            f"{doc.get('completion_reason')!r}")
    return doc


@dsl.component(name="deploy-inference-service", cache=False)
def deploy_inference_service(service: dict, operator_url: str = "",
                             namespace: str = "",
                             timeout_s: float = 300.0,
                             poll_s: float = 0.5) -> dict:
    """Apply an InferenceService spec and wait until it reports ready —
    the train→deploy pipeline tail (SURVEY.md §3.4's deploy step)."""
    import json
    import time

    base = _base(operator_url)
    ns = namespace or service.get("namespace") or "default"
    name = service["name"]
    _api(base, f"/apis/v1/namespaces/{ns}/inferenceservices",
         payload=json.dumps(service).encode(), method="POST")
    deadline = time.time() + timeout_s
    doc: dict = {}
    while time.time() < deadline:
        doc = _api(base, f"/apis/v1/namespaces/{ns}/inferenceservices/{name}")
        if doc.get("ready"):
            return doc
        time.sleep(poll_s)
    raise RuntimeError(f"inference service {ns}/{name} never became ready")
