"""Module-level example components + pipeline, importable by fnRef.

IR-submitted pipelines (POST /apis/v1/pipelines) resolve their component
functions by ``module:qualname`` — this module is the shipped example of
that contract (the reference analogue: reusable container components).
The pipeline exercises every IR construct: parameters, data deps, a
ParallelFor fan-out, a trigger condition, and an exit handler.
"""

from __future__ import annotations

from kubeflow_tpu.pipelines import dsl


@dsl.component
def score_shard(shard: int, scale: float = 1.0) -> float:
    return shard * scale


@dsl.component
def summarize(n: int, scale: float) -> float:
    # n shards scored shard*scale: the closed-form sum the fan-out computes
    return scale * n * (n - 1) / 2


@dsl.component
def alert(total: float) -> str:
    return f"total={total}"


@dsl.component
def cleanup() -> str:
    return "cleaned"


@dsl.pipeline(name="shard-scores")
def shard_scores(n: int = 3, scale: float = 2.0):
    with dsl.ExitHandler(cleanup()):
        # static fan-out (the runner expands ParallelFor over static lists
        # or pipeline parameters; dynamic task-output fan-out needs the
        # dynamic driver and is out of the example's scope)
        with dsl.ParallelFor([0, 1, 2]) as shard:
            score_shard(shard=shard, scale=scale)
        total = summarize(n=n, scale=scale)
        with dsl.Condition(total.output > 1.0):
            alert(total=total.output)
