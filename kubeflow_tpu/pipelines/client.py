"""Pipeline client + run store + recurring runs.

The ml-pipeline API-server surface (SURVEY.md §2.5: PipelineService /
RunService / ExperimentService / RecurringRunService) reduced to its
capability set: register pipelines, create/list/get runs, recurring runs on
an interval schedule (the ScheduledWorkflow controller role).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Optional

from kubeflow_tpu.pipelines import dsl
from kubeflow_tpu.pipelines.runner import LocalRunner, RunResult, TaskState


@dataclasses.dataclass
class RecurringRun:
    name: str
    pipeline: str
    interval_seconds: float
    arguments: dict[str, Any] = dataclasses.field(default_factory=dict)
    enabled: bool = True
    last_fire: float = 0.0
    max_concurrency: int = 1
    run_ids: list[str] = dataclasses.field(default_factory=list)
    last_error: str = ""
    _inflight: int = 0


class PipelineClient:
    """kfp.Client-equivalent over a LocalRunner backend."""

    def __init__(self, runner: LocalRunner):
        self.runner = runner
        self._pipelines: dict[str, dsl.Pipeline] = {}
        self._runs: dict[str, RunResult] = {}
        self._recurring: dict[str, RecurringRun] = {}
        self._lock = threading.Lock()

    # ---------------- pipelines ----------------

    def upload_pipeline(self, pipe: dsl.Pipeline,
                        name: Optional[str] = None) -> str:
        name = name or pipe.name
        with self._lock:
            self._pipelines[name] = pipe
        return name

    def list_pipelines(self) -> list[str]:
        with self._lock:
            return sorted(self._pipelines)

    # ---------------- runs ----------------

    def create_run(self, pipeline: str | dsl.Pipeline,
                   arguments: Optional[dict[str, Any]] = None,
                   run_id: Optional[str] = None) -> RunResult:
        pipe = (pipeline if isinstance(pipeline, dsl.Pipeline)
                else self._pipelines[pipeline])
        result = self.runner.run(pipe, arguments=arguments, run_id=run_id)
        with self._lock:
            self._runs[result.run_id] = result
        return result

    def get_run(self, run_id: str) -> Optional[RunResult]:
        with self._lock:
            return self._runs.get(run_id)

    def list_runs(self, pipeline: Optional[str] = None) -> list[RunResult]:
        with self._lock:
            runs = list(self._runs.values())
        if pipeline:
            runs = [r for r in runs if r.run_id.startswith(pipeline)]
        return sorted(runs, key=lambda r: r.run_id)

    # ---------------- recurring runs ----------------

    def create_recurring_run(self, name: str, pipeline: str,
                             interval_seconds: float,
                             arguments: Optional[dict[str, Any]] = None,
                             max_concurrency: int = 1) -> RecurringRun:
        if pipeline not in self._pipelines:
            raise KeyError(f"unknown pipeline {pipeline!r}")
        rr = RecurringRun(name=name, pipeline=pipeline,
                          interval_seconds=interval_seconds,
                          arguments=dict(arguments or {}),
                          max_concurrency=max_concurrency)
        with self._lock:
            self._recurring[name] = rr
        return rr

    def disable_recurring_run(self, name: str) -> None:
        with self._lock:
            self._recurring[name].enabled = False

    def tick(self, now: Optional[float] = None) -> list[RunResult]:
        """Fire due recurring runs (the scheduled-workflow controller's
        reconcile step; call from a timer loop in production)."""
        now = time.time() if now is None else now
        fired = []
        with self._lock:
            # claim due jobs under the lock (stamp last_fire + reserve a
            # concurrency ticket) so concurrent ticks can't double-fire
            due = []
            for rr in self._recurring.values():
                if (rr.enabled
                        and now - rr.last_fire >= rr.interval_seconds
                        and rr._inflight < rr.max_concurrency):
                    rr.last_fire = now
                    rr._inflight += 1
                    due.append(rr)
        for rr in due:
            # one failing schedule must not starve the others this tick
            try:
                result = self.create_run(
                    rr.pipeline, arguments=rr.arguments,
                    run_id=f"{rr.pipeline}-{rr.name}-{int(now)}")
            except Exception as e:
                with self._lock:
                    rr._inflight -= 1
                    rr.last_error = f"{type(e).__name__}: {e}"
                continue
            with self._lock:
                rr._inflight -= 1
                rr.run_ids.append(result.run_id)
            fired.append(result)
        return fired
