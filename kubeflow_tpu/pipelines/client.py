"""Pipeline client + run store + recurring runs.

The ml-pipeline API-server surface (SURVEY.md §2.5: PipelineService /
RunService / ExperimentService / RecurringRunService) reduced to its
capability set: register pipelines (as traced Python or compiled IR
documents), create/list/get runs, recurring runs on an interval schedule
(the ScheduledWorkflow controller role).

Durability (the reference's MySQL role): when constructed with ``store``
(a metadata backend), IR-uploaded pipelines and recurring-run schedules
are persisted as contexts (+ a status execution for the mutable enable /
last-fire state), and ``resume_persisted()`` reloads them after a daemon
restart. Run *status* is always durable — the runner writes it through
the same store (``runner.run_status``) — so ``get_run``/``list_runs``
fall back to the persisted record for runs started by a previous process.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import threading
import time
from typing import Any, Optional

from kubeflow_tpu.pipelines import dsl
from kubeflow_tpu.pipelines.compiler import pipeline_from_ir
from kubeflow_tpu.pipelines.runner import (
    LocalRunner, RunResult, TaskResult, TaskState, run_status,
    sanitize_run_component, validate_run_id,
)

PIPELINE_IR_TYPE = "pipeline_ir"
RECURRING_TYPE = "recurring_run"
RECURRING_STATUS_TYPE = "recurring_run_status"


@dataclasses.dataclass
class RecurringRun:
    name: str
    pipeline: str
    interval_seconds: float
    arguments: dict[str, Any] = dataclasses.field(default_factory=dict)
    enabled: bool = True
    last_fire: float = 0.0
    max_concurrency: int = 1
    run_ids: list[str] = dataclasses.field(default_factory=list)
    last_error: str = ""
    _inflight: int = 0


class PipelineClient:
    """kfp.Client-equivalent over a LocalRunner backend."""

    def __init__(self, runner: LocalRunner, store=None):
        self.runner = runner
        # default the durability backend to the runner's lineage store so
        # one WAL holds pipelines, schedules, and run state together
        self.store = store if store is not None else runner.metadata
        self._pipelines: dict[str, dsl.Pipeline] = {}
        self._runs: dict[str, RunResult] = {}
        self._recurring: dict[str, RecurringRun] = {}
        self._rr_status_ids: dict[str, int] = {}
        self._fire_seq = itertools.count()
        self._lock = threading.Lock()

    # ---------------- pipelines ----------------

    def upload_pipeline(self, pipe: dsl.Pipeline,
                        name: Optional[str] = None) -> str:
        name = name or pipe.name
        with self._lock:
            self._pipelines[name] = pipe
        return name

    def upload_ir(self, ir: dict, name: Optional[str] = None) -> str:
        """Register a compiled IR document (the POST /pipelines surface).
        Persisted: a daemon restart re-materializes it. Re-uploading under
        the same name replaces the stored document (contexts are immutable
        in the store, so the document lives in a mutable execution)."""
        pipe = pipeline_from_ir(ir)
        name = name or pipe.name
        with self._lock:
            # store writes stay under the lock: concurrent uploads of the
            # same name must not each get-or-create a doc execution
            self._pipelines[name] = pipe
            if self.store is not None:
                cid = self.store.put_context(PIPELINE_IR_TYPE, name)
                did = self._doc_execution_id(
                    cid, "pipeline_ir_doc", f"{name}/ir")
                self.store.update_execution(
                    did, state="ACTIVE", properties={"ir": json.dumps(ir)})
        return name

    def _doc_execution_id(self, cid: int, ex_type: str, ex_name: str) -> int:
        """Get-or-create the mutable document execution under a context."""
        for ex in self.store.executions_in_context(cid):
            if ex.type == ex_type:
                return ex.id
        eid = self.store.put_execution(ex_type, name=ex_name, state="ACTIVE")
        self.store.associate(cid, eid)
        return eid

    def list_pipelines(self) -> list[str]:
        with self._lock:
            return sorted(self._pipelines)

    # ---------------- runs ----------------

    def create_run(self, pipeline: str | dsl.Pipeline,
                   arguments: Optional[dict[str, Any]] = None,
                   run_id: Optional[str] = None) -> RunResult:
        pipe = (pipeline if isinstance(pipeline, dsl.Pipeline)
                else self._pipelines[pipeline])
        result = self.runner.run(pipe, arguments=arguments, run_id=run_id)
        with self._lock:
            self._runs[result.run_id] = result
        return result

    def create_run_async(self, pipeline: str,
                         arguments: Optional[dict[str, Any]] = None,
                         run_id: Optional[str] = None) -> str:
        """Launch a run in a background thread and return its id at once
        (the POST /runs 202 contract). A launch that fails before the
        runner can persist anything (e.g. missing required arguments)
        still records a FAILED status — a 202'd run id must never 404
        forever."""
        import uuid

        if pipeline not in self.list_pipelines():
            raise KeyError(f"unknown pipeline {pipeline!r}")
        if run_id is None:
            run_id = (f"{sanitize_run_component(pipeline)}-"
                      f"{uuid.uuid4().hex[:8]}")
        else:
            # reject bad CLIENT-supplied ids HERE (synchronous 400), not
            # in the background thread where the error only reaches the
            # store; auto-generated ids sanitize the name instead
            validate_run_id(run_id)

        def target():
            try:
                self.create_run(pipeline, arguments=arguments, run_id=run_id)
            except BaseException as e:
                self._record_failed_launch(run_id, pipeline, e)

        threading.Thread(target=target, daemon=True,
                         name=f"kft-pipeline-{run_id}").start()
        return run_id

    def _record_failed_launch(self, run_id: str, pipeline: str,
                              err: BaseException) -> None:
        if self.store is None:
            return
        try:
            cid = self.store.put_context(
                "pipeline_run", run_id, properties={"pipeline": pipeline})
            sid = self._doc_execution_id(
                cid, "pipeline_run_status", f"{run_id}/status")
            self.store.update_execution(
                sid, state="FAILED",
                properties={"pipeline": pipeline, "tasks": {},
                            "error": f"{type(err).__name__}: {err}"})
        except Exception:
            pass   # persistence is best-effort here; the thread must not die

    def get_run(self, run_id: str) -> Optional[RunResult]:
        with self._lock:
            run = self._runs.get(run_id)
        if run is not None:
            return run
        return self._run_from_store(run_id)

    def _run_from_store(self, run_id: str) -> Optional[RunResult]:
        """Reconstruct a RunResult from the persisted status record (runs
        started by a previous process, or in flight in another thread)."""
        if self.store is None:
            return None
        st = run_status(self.store, run_id)
        return self._run_from_status(run_id, st)

    @staticmethod
    def _run_from_status(run_id: str, st: Optional[dict]
                         ) -> Optional[RunResult]:
        if st is None:
            return None
        state_map = {"RUNNING": TaskState.RUNNING,
                     "SUCCEEDED": TaskState.SUCCEEDED,
                     "FAILED": TaskState.FAILED}
        return RunResult(
            run_id=run_id,
            state=state_map.get(st["state"], TaskState.PENDING),
            tasks={n: TaskResult(name=n, state=TaskState(s))
                   for n, s in (st.get("tasks") or {}).items()},
            params={},
            error=st.get("error", ""),
        )

    def list_runs(self, pipeline: Optional[str] = None) -> list[RunResult]:
        with self._lock:
            runs = dict(self._runs)
        # merge persisted runs from earlier processes (in-proc store only:
        # it exposes the context table; remote stores list via run ids).
        # Status is read straight off each context's executions — going
        # through run_status would re-resolve every context by name and
        # make this quadratic in run history.
        contexts = getattr(self.store, "contexts", None)
        if contexts is not None:
            for c in list(contexts.values()):
                if c.type != "pipeline_run" or c.name in runs:
                    continue
                for ex in self.store.executions_in_context(c.id):
                    if ex.type == "pipeline_run_status":
                        rec = self._run_from_status(c.name, {
                            "state": ex.state,
                            "tasks": ex.properties.get("tasks", {}),
                            "error": ex.properties.get("error", ""),
                        })
                        if rec is not None:
                            runs[c.name] = rec
                        break
        out = list(runs.values())
        if pipeline:
            # run ids embed the SANITIZED pipeline name (odd-but-legal
            # names are rewritten), so the filter must sanitize too
            pfx = sanitize_run_component(pipeline)
            out = [r for r in out if r.run_id.startswith(pfx)]
        return sorted(out, key=lambda r: r.run_id)

    # ---------------- recurring runs ----------------

    def create_recurring_run(self, name: str, pipeline: str,
                             interval_seconds: float,
                             arguments: Optional[dict[str, Any]] = None,
                             max_concurrency: int = 1) -> RecurringRun:
        if pipeline not in self._pipelines:
            raise KeyError(f"unknown pipeline {pipeline!r}")
        rr = RecurringRun(name=name, pipeline=pipeline,
                          interval_seconds=interval_seconds,
                          arguments=dict(arguments or {}),
                          max_concurrency=max_concurrency)
        with self._lock:
            # registry + store writes together: concurrent creates of the
            # same schedule must not duplicate the status execution
            self._recurring[name] = rr
            self._persist_recurring(rr)
        return rr

    def disable_recurring_run(self, name: str) -> None:
        with self._lock:
            rr = self._recurring[name]
            rr.enabled = False
        self._sync_recurring_status(rr)

    def list_recurring(self) -> list[RecurringRun]:
        """Snapshot of the recurring schedules (safe to iterate while
        other requests mutate the registry)."""
        with self._lock:
            return [dataclasses.replace(rr, run_ids=list(rr.run_ids))
                    for rr in self._recurring.values()]

    def _persist_recurring(self, rr: RecurringRun) -> None:
        """The WHOLE recurring record (spec + mutable state) lives in the
        status execution so re-creating a schedule replaces it."""
        if self.store is None:
            return
        cid = self.store.put_context(RECURRING_TYPE, rr.name)
        self._rr_status_id(rr.name, cid)
        self._sync_recurring_status(rr)

    def _rr_status_id(self, name: str, cid: int) -> Optional[int]:
        if self.store is None:
            return None
        if name not in self._rr_status_ids:
            self._rr_status_ids[name] = self._doc_execution_id(
                cid, RECURRING_STATUS_TYPE, f"{name}/status")
        return self._rr_status_ids[name]

    def _sync_recurring_status(self, rr: RecurringRun) -> None:
        if self.store is None or rr.name not in self._rr_status_ids:
            return
        self.store.update_execution(
            self._rr_status_ids[rr.name],
            state="ENABLED" if rr.enabled else "DISABLED",
            properties={"spec": json.dumps({
                "pipeline": rr.pipeline,
                "interval_seconds": rr.interval_seconds,
                "arguments": rr.arguments,
                "max_concurrency": rr.max_concurrency,
            }), "last_fire": rr.last_fire, "run_ids": list(rr.run_ids)})

    # ---------------- restart resume (persistence-agent role) -----------

    def resume_persisted(self) -> list[str]:
        """Reload IR pipelines + recurring schedules persisted by an
        earlier process. Returns the resumed pipeline names. Requires an
        in-proc store (context table access)."""
        contexts = getattr(self.store, "contexts", None)
        if contexts is None:
            return []
        resumed = []
        for c in list(contexts.values()):
            if c.type != PIPELINE_IR_TYPE:
                continue
            try:
                did = self._doc_execution_id(
                    c.id, "pipeline_ir_doc", f"{c.name}/ir")
                ir = json.loads(self.store.get_execution(did)
                                .properties["ir"])
                pipe = pipeline_from_ir(ir)
            except Exception:
                continue   # component module gone — skip, don't wedge boot
            with self._lock:
                self._pipelines.setdefault(c.name, pipe)
            resumed.append(c.name)
        for c in list(contexts.values()):
            if c.type != RECURRING_TYPE:
                continue
            sid = self._rr_status_id(c.name, c.id)
            ex = self.store.get_execution(sid)
            if "spec" not in ex.properties:
                continue
            spec = json.loads(ex.properties["spec"])
            if spec["pipeline"] not in self._pipelines:
                continue
            rr = RecurringRun(
                name=c.name, pipeline=spec["pipeline"],
                interval_seconds=spec["interval_seconds"],
                arguments=dict(spec.get("arguments", {})),
                max_concurrency=spec.get("max_concurrency", 1),
                enabled=ex.state != "DISABLED",
                last_fire=float(ex.properties.get("last_fire", 0.0)),
                run_ids=list(ex.properties.get("run_ids", [])))
            with self._lock:
                self._recurring.setdefault(c.name, rr)
        return resumed

    def tick(self, now: Optional[float] = None) -> list[RunResult]:
        """Fire due recurring runs (the scheduled-workflow controller's
        reconcile step; call from a timer loop in production)."""
        now = time.time() if now is None else now
        fired = []
        with self._lock:
            # claim due jobs under the lock (stamp last_fire + reserve a
            # concurrency ticket) so concurrent ticks can't double-fire
            due = []
            for rr in self._recurring.values():
                if (rr.enabled
                        and now - rr.last_fire >= rr.interval_seconds
                        and rr._inflight < rr.max_concurrency):
                    rr.last_fire = now
                    rr._inflight += 1
                    due.append(rr)
        for rr in due:
            # one failing schedule must not starve the others this tick
            try:
                # ms precision + a process-wide sequence: sub-second
                # intervals must never reuse a run_id (the store keys run
                # state by it; a duplicate would shadow the second run)
                result = self.create_run(
                    rr.pipeline, arguments=rr.arguments,
                    run_id=f"{sanitize_run_component(rr.pipeline)}-"
                           f"{sanitize_run_component(rr.name)}-"
                           f"{int(now * 1000)}.{next(self._fire_seq)}")
            except Exception as e:
                with self._lock:
                    rr._inflight -= 1
                    rr.last_error = f"{type(e).__name__}: {e}"
                continue
            with self._lock:
                rr._inflight -= 1
                rr.run_ids.append(result.run_id)
            fired.append(result)
            self._sync_recurring_status(rr)
        return fired
