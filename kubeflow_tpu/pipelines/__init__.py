"""Pipelines layer — KFP-equivalent DSL, compiler, DAG runner, lineage
(SURVEY.md §2.5)."""

from kubeflow_tpu.pipelines.client import PipelineClient, RecurringRun
from kubeflow_tpu.pipelines.compiler import (
    Compiler, compile_pipeline, load_ir, pipeline_from_ir,
)
from kubeflow_tpu.pipelines.dsl import (
    Artifact, Condition, Dataset, ExitHandler, Input, Metrics, Model, Output,
    ParallelFor, Pipeline, Task, component, pipeline,
)
from kubeflow_tpu.pipelines.runner import (
    LocalRunner, RunResult, TaskResult, TaskState, run_status,
)

__all__ = [
    "Artifact", "Compiler", "Condition", "Dataset", "ExitHandler", "Input",
    "LocalRunner", "Metrics", "Model", "Output", "ParallelFor", "Pipeline",
    "PipelineClient", "RecurringRun", "RunResult", "Task", "TaskResult",
    "TaskState", "compile_pipeline", "component", "load_ir", "pipeline",
    "pipeline_from_ir", "run_status",
]
