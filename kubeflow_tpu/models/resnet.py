"""ResNet-v1.5 family for the JAXJob data-parallel milestone
(BASELINE.json:8: 'ResNet-50 data-parallel on a v4-8 pod slice').

Functional JAX, NHWC, bf16 compute / f32 params+stats. BatchNorm running
stats are explicit state threaded through `forward` (functional — no mutable
modules); in data-parallel training the batch statistics are computed over the
per-device batch and the running stats EMA-synced by the gradient all-reduce's
sibling psum emitted from sharding (stats are replicated params-like state).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: object = jnp.bfloat16


def resnet50(**kw) -> ResNetConfig:
    return ResNetConfig(**kw)


def resnet18(**kw) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(2, 2, 2, 2), **kw)


def resnet_tiny(**kw) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=10, **kw)


def _conv_init(key, shape):  # HWIO, He init
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5


def _bn_init(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
    }


def _bn_stats(c):
    return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}


def init_params(rng: jax.Array, cfg: ResNetConfig):
    """Returns (params, batch_stats)."""
    keys = iter(jax.random.split(rng, 1024))
    params: dict = {}
    stats: dict = {}

    params["stem"] = {"conv": _conv_init(next(keys), (7, 7, 3, cfg.width)),
                      "bn": _bn_init(cfg.width)}
    stats["stem"] = _bn_stats(cfg.width)

    in_c = cfg.width
    for si, n_blocks in enumerate(cfg.stage_sizes):
        out_c = cfg.width * (2 ** si) * 4
        mid_c = cfg.width * (2 ** si)
        stage_p, stage_s = [], []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            blk_p = {
                "conv1": _conv_init(next(keys), (1, 1, in_c, mid_c)),
                "bn1": _bn_init(mid_c),
                "conv2": _conv_init(next(keys), (3, 3, mid_c, mid_c)),
                "bn2": _bn_init(mid_c),
                "conv3": _conv_init(next(keys), (1, 1, mid_c, out_c)),
                "bn3": _bn_init(out_c),
            }
            blk_s = {"bn1": _bn_stats(mid_c), "bn2": _bn_stats(mid_c),
                     "bn3": _bn_stats(out_c)}
            if in_c != out_c or stride != 1:
                blk_p["proj"] = _conv_init(next(keys), (1, 1, in_c, out_c))
                blk_p["proj_bn"] = _bn_init(out_c)
                blk_s["proj_bn"] = _bn_stats(out_c)
            stage_p.append(blk_p)
            stage_s.append(blk_s)
            in_c = out_c
        params[f"stage{si}"] = stage_p
        stats[f"stage{si}"] = stage_s

    params["head"] = {
        "w": jax.random.normal(next(keys), (in_c, cfg.num_classes), jnp.float32)
        * (1.0 / in_c) ** 0.5,
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, stats


def _conv(x, w, stride, dtype):
    return jax.lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p, s, train: bool, momentum=0.9, eps=1e-5):
    """Returns (y, new_stats)."""
    xf = x.astype(jnp.float32)
    if train:
        mean = xf.mean(axis=(0, 1, 2))
        var = xf.var(axis=(0, 1, 2))
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    y = (xf - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_s


def forward(params, stats, images, cfg: ResNetConfig, train: bool = True):
    """images: [B,H,W,3] -> (logits [B,classes] f32, new_stats)."""
    dt = cfg.dtype
    new_stats: dict = {}
    x = _conv(images, params["stem"]["conv"], 2, dt)
    x, new_stats["stem"] = _bn(x, params["stem"]["bn"], stats["stem"], train)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )

    for si in range(len(cfg.stage_sizes)):
        stage_stats = []
        for bi, blk in enumerate(params[f"stage{si}"]):
            s = stats[f"stage{si}"][bi]
            ns: dict = {}
            stride = 2 if (si > 0 and bi == 0) else 1
            residual = x
            y = _conv(x, blk["conv1"], 1, dt)
            y, ns["bn1"] = _bn(y, blk["bn1"], s["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv2"], stride, dt)
            y, ns["bn2"] = _bn(y, blk["bn2"], s["bn2"], train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv3"], 1, dt)
            y, ns["bn3"] = _bn(y, blk["bn3"], s["bn3"], train)
            if "proj" in blk:
                residual = _conv(x, blk["proj"], stride, dt)
                residual, ns["proj_bn"] = _bn(
                    residual, blk["proj_bn"], s["proj_bn"], train
                )
            x = jax.nn.relu(y + residual)
            stage_stats.append(ns)
        new_stats[f"stage{si}"] = stage_stats

    x = x.astype(jnp.float32).mean(axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_stats
