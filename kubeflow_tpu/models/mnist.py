"""MNIST CNN — the CPU-only baseline config (BASELINE.json:7, 'TFJob
single-worker MNIST CNN'). Functional JAX, NHWC (TPU-native layout)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_params(rng: jax.Array, dtype=jnp.float32):
    k1, k2, k3, k4 = jax.random.split(rng, 4)

    def conv(key, shape):  # HWIO
        fan_in = shape[0] * shape[1] * shape[2]
        return jax.random.normal(key, shape, dtype) * (2.0 / fan_in) ** 0.5

    def dense(key, shape):
        return jax.random.normal(key, shape, dtype) * (2.0 / shape[0]) ** 0.5

    return {
        "conv1": conv(k1, (3, 3, 1, 32)),
        "conv2": conv(k2, (3, 3, 32, 64)),
        "fc1": dense(k3, (7 * 7 * 64, 128)),
        "b1": jnp.zeros((128,), dtype),
        "fc2": dense(k4, (128, 10)),
        "b2": jnp.zeros((10,), dtype),
    }


def forward(params, images):
    """images: [B, 28, 28, 1] -> logits [B, 10]."""
    x = jax.lax.conv_general_dilated(
        images, params["conv1"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )
    x = jax.lax.conv_general_dilated(
        x, params["conv2"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"] + params["b1"])
    return x @ params["fc2"] + params["b2"]
