"""Llama-3 model family, TPU-first.

Design (deliberately not a torch translation — SURVEY.md §7 design stance):

- Pure-functional: params are a pytree of arrays; `forward` is a jittable
  function. No module framework in the hot path.
- **Scan over layers**: all transformer blocks are stacked along a leading
  `layers` axis and executed with `jax.lax.scan`, so XLA compiles ONE block
  regardless of depth (compile time O(1) in n_layers) and remat policy applies
  uniformly.
- **Logical axes everywhere**: every param/activation carries logical axis
  names resolved against a mesh by `parallel.sharding` rules — the same model
  runs DP/FSDP/TP/SP by swapping the rule table.
- bf16 compute, f32 params (casting at the boundary), f32 softmax/norms.

Reference parity: the reference (Kubeflow) ships no model code — models live
in user containers. This module is the first-party data plane SURVEY.md §7
requires, sized for the BASELINE.json configs (Llama-3-8B serving, 70B FSDP).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import attention, decode_attention
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import apply_rope, rope_frequencies
from kubeflow_tpu.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    rope_scaling: str | None = "llama3"
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"            # "xla" | "flash" | "pallas"
    attn_block: int = 512             # flash-kernel tile (VMEM budget knob)
    remat: str = "full"               # "none" | "full" | "dots"
    z_loss: float = 1e-4
    # MoE (0 experts = dense MLP). Mixtral-style: the FFN becomes a routed
    # mixture; attention/embeddings unchanged (SURVEY.md §2.7 'EP').
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_token(self, seq: int | None = None) -> float:
        """Approx model FLOPs per token (fwd+bwd = 3x fwd matmul FLOPs).
        With ``seq`` the causal attention-score FLOPs (QK^T and PV, avg
        context seq/2) are included — the MFU-honest accounting. Remat
        recompute is deliberately NOT counted (it lowers reported MFU).
        For MoE only the top-k experts' FFN FLOPs are active per token."""
        d, m, v = self.dim, self.mlp_dim, self.vocab_size
        attn_proj = 2 * d * (self.n_heads + 2 * self.n_kv_heads) * self.head_dim
        attn_out = 2 * self.n_heads * self.head_dim * d
        attn_score = (2 * seq * self.n_heads * self.head_dim) if seq else 0
        active_ffns = self.moe_top_k if self.n_experts else 1
        mlp = 2 * 3 * d * m * active_ffns
        per_layer = attn_proj + attn_out + attn_score + mlp
        return 3 * (self.n_layers * per_layer + 2 * d * v)

    def moe_config(self):
        from kubeflow_tpu.parallel.moe import MoEConfig

        return MoEConfig(
            dim=self.dim, mlp_dim=self.mlp_dim, n_experts=self.n_experts,
            top_k=self.moe_top_k, capacity_factor=self.moe_capacity_factor,
            dtype=self.dtype)


def llama3_8b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama3_70b(**kw) -> LlamaConfig:
    return LlamaConfig(
        dim=8192, n_layers=80, n_heads=64, n_kv_heads=8, mlp_dim=28672, **kw
    )


def llama_1b(**kw) -> LlamaConfig:
    """Single-v5e-chip benchmark config (16G HBM)."""
    return LlamaConfig(
        vocab_size=32768, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        mlp_dim=5632, max_seq=2048, tie_embeddings=True, **kw
    )


def llama_tiny(**kw) -> LlamaConfig:
    """CI config: runs on CPU in seconds."""
    return LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_seq=128, rope_scaling=None, tie_embeddings=True, **kw
    )


def llama_moe_8x(base: LlamaConfig | None = None, n_experts: int = 8,
                 **kw) -> LlamaConfig:
    """Mixtral-style MoE variant of any base config (default 8 experts)."""
    base = base or llama3_8b()
    return dataclasses.replace(base, n_experts=n_experts, **kw)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: LlamaConfig, dtype=jnp.float32):
    """Initialize parameters (stacked along a leading `layers` axis)."""
    k_embed, k_layers, k_head = jax.random.split(rng, 3)
    d, h, kv, hd, m, L = (
        cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.mlp_dim,
        cfg.n_layers,
    )

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": jnp.ones((L, d), dtype),
        "mlp_norm": jnp.ones((L, d), dtype),
        "wq": dense(ks[0], (L, d, h, hd), d),
        "wk": dense(ks[1], (L, d, kv, hd), d),
        "wv": dense(ks[2], (L, d, kv, hd), d),
        "wo": dense(ks[3], (L, h, hd, d), h * hd),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        layers.update({
            "moe_router": dense(ks[7], (L, d, E), d),
            "w_gate": dense(ks[4], (L, E, d, m), d),
            "w_up": dense(ks[5], (L, E, d, m), d),
            "w_down": dense(ks[6], (L, E, m, d), m),
        })
    else:
        layers.update({
            "w_gate": dense(ks[4], (L, d, m), d),
            "w_up": dense(ks[5], (L, d, m), d),
            "w_down": dense(ks[6], (L, m, d), m),
        })
    params = {
        "embed": dense(k_embed, (cfg.vocab_size, d), d),
        "layers": layers,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_head, (d, cfg.vocab_size), d)
    return params


def param_logical_axes(cfg: LlamaConfig):
    """Logical axis names per param, mirroring init_params' structure."""
    layer_axes = {
        "attn_norm": ("layers", "embed"),
        "mlp_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
    }
    if cfg.n_experts:
        layer_axes.update({
            "moe_router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        })
    else:
        layer_axes.update({
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        })
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layer_axes,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# int8 weight serving (serving/quant.py quantizes the tree; these helpers
# are the per-tile dequant the serving call sites share)
# ---------------------------------------------------------------------------

def qmm(spec, x, tree, name, cfg: LlamaConfig):
    """Matmul over an int8-quantized weight ``name`` (``name_q`` int8 +
    ``name_s`` f32 per-output-channel scales in ``tree``): the HBM read
    is one byte per param, the tile upcasts to the compute dtype inside
    the fused einsum, and the scales multiply the OUTPUT tile — a dense
    dequantized weight never exists."""
    out = jnp.einsum(spec, x, tree[name + "_q"].astype(cfg.dtype))
    return out * tree[name + "_s"].astype(cfg.dtype)


def embed_tokens(params, tokens, cfg: LlamaConfig):
    """Embedding lookup, quant-aware: int8 tables dequant the gathered
    rows with their per-vocab-row scale. The unquantized branch is the
    exact expression the call sites used before — the quant-off program
    stays bitwise-identical."""
    if "embed_q" in params:
        rows = params["embed_q"].astype(cfg.dtype)[tokens]
        return rows * params["embed_s"].astype(cfg.dtype)[tokens][..., None]
    return params["embed"].astype(cfg.dtype)[tokens]


def quant_head_logits(params, x, cfg: LlamaConfig):
    """LM-head matmul over the int8 tree: tied embeddings reuse the
    embedding table (its per-vocab-ROW scales become per-output-channel
    scales of the transposed head); untied heads carry their own
    per-vocab-channel scales. x: [..., D] -> [..., V] compute dtype."""
    if cfg.tie_embeddings:
        out = jnp.einsum("...d,dv->...v", x,
                         params["embed_q"].T.astype(cfg.dtype))
        return out * params["embed_s"].astype(cfg.dtype)
    out = jnp.einsum("...d,dv->...v", x,
                     params["lm_head_q"].astype(cfg.dtype))
    return out * params["lm_head_s"].astype(cfg.dtype)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _ffn(h, lp, cfg: LlamaConfig, token_mask=None):
    """FFN half of a block on the normed input h: (delta, aux_loss_scalar).
    Dense SwiGLU, or the routed MoE mixture when cfg.n_experts > 0.
    ``token_mask`` [B, S]: serving paths exclude pad/idle rows from MoE
    routing (they would steal expert capacity from real tokens)."""
    if cfg.n_experts:
        from kubeflow_tpu.parallel.moe import moe_aux_total, moe_layer

        moe_params = {"router": lp["moe_router"], "w_gate": lp["w_gate"],
                      "w_up": lp["w_up"], "w_down": lp["w_down"]}
        y, aux = moe_layer(moe_params, h, cfg.moe_config(),
                           token_mask=token_mask)
        return y, moe_aux_total(aux)
    if "w_gate_q" in lp:
        gate = qmm("bsd,dm->bsm", h, lp, "w_gate", cfg)
        up = qmm("bsd,dm->bsm", h, lp, "w_up", cfg)
        ff = constrain(jax.nn.silu(gate) * up, ("batch", "seq", "act_mlp"))
        down = qmm("bsm,md->bsd", ff, lp, "w_down", cfg)
        return down, jnp.zeros((), jnp.float32)
    gate = jnp.einsum("bsd,dm->bsm", h, lp["w_gate"].astype(cfg.dtype))
    up = jnp.einsum("bsd,dm->bsm", h, lp["w_up"].astype(cfg.dtype))
    ff = constrain(jax.nn.silu(gate) * up, ("batch", "seq", "act_mlp"))
    down = jnp.einsum("bsm,md->bsd", ff, lp["w_down"].astype(cfg.dtype))
    return down, jnp.zeros((), jnp.float32)


def _block(x, lp, inv_freq, positions, cfg: LlamaConfig, mesh=None):
    """One transformer block. x: [B,S,D] in compute dtype.
    Returns (x, aux_loss_scalar)."""
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"].astype(cfg.dtype))
    q = constrain(q, ("batch", "seq", "act_heads", None))
    k = constrain(k, ("batch", "seq", None, None))
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    if cfg.attn_impl in ("ring", "ulysses"):
        from kubeflow_tpu.parallel.ring_attention import (
            ring_attention, ulysses_attention,
        )

        if mesh is None:
            raise ValueError(f"attn_impl={cfg.attn_impl!r} requires mesh=")
        attn_fn = ring_attention if cfg.attn_impl == "ring" else ulysses_attention
        o = attn_fn(q, k, v, mesh, causal=True)
    else:
        o = attention(q, k, v, causal=True, impl=cfg.attn_impl,
                      block_q=cfg.attn_block, block_kv=cfg.attn_block)
    o = jnp.einsum("bshk,hkd->bsd", o, lp["wo"].astype(cfg.dtype))
    x = x + constrain(o, ("batch", "seq", "act_embed"))

    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    down, aux = _ffn(h, lp, cfg)
    return x + constrain(down, ("batch", "seq", "act_embed")), aux


def _remat_wrap(fn, cfg: LlamaConfig):
    if cfg.remat == "none":
        return fn
    if cfg.remat == "dots":
        policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
        return jax.checkpoint(fn, policy=policy)
    return jax.checkpoint(fn)


def forward(params, tokens, cfg: LlamaConfig, positions=None, mesh=None,
            return_aux: bool = False):
    """Full-sequence forward. tokens: [B,S] int32 -> logits [B,S,V] (f32).

    `mesh` is only needed for the context-parallel attention impls
    ("ring"/"ulysses"), which run shard_map collectives over it.
    With ``return_aux`` returns (logits, aux) where aux carries the summed
    MoE penalties (zero for dense configs) — add it to the training loss.
    """
    if positions is None:
        positions = jnp.arange(tokens.shape[1])[None, :]
    inv_freq = jnp.asarray(rope_frequencies(
        cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
        original_max_seq=cfg.max_seq,
    ))
    # Embedding lookup, SPMD-clean: a row gather from the (vocab=tensor,
    # embed=fsdp)-sharded table makes the partitioner emit an "involuntary
    # full rematerialization" of the [B,S,D] activation (it can't reshard
    # gather output efficiently). Explicitly replicating the bf16-cast
    # table first makes the gather local and the batch/seq partition a
    # free slice — the same table all-gather XLA's fallback pays, minus
    # the (much larger) activation replication, and warning-free.
    table = constrain(params["embed"].astype(cfg.dtype), (None, None))
    x = table[tokens]
    x = constrain(x, ("batch", "seq", "act_embed"))

    block = _remat_wrap(
        lambda x, lp: _block(x, lp, inv_freq, positions, cfg, mesh), cfg
    )
    x, aux_per_layer = jax.lax.scan(block, x, params["layers"])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
    logits = constrain(logits, ("batch", "seq", None))
    logits = logits.astype(jnp.float32)
    if return_aux:
        return logits, {"moe_aux": jnp.sum(aux_per_layer)}
    return logits


# ---------------------------------------------------------------------------
# KV-cached decoding (serving path)
# ---------------------------------------------------------------------------

def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None):
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "len": jnp.zeros((batch,), jnp.int32),
    }


def prefill(params, tokens, cfg: LlamaConfig, cache, lengths=None):
    """Run the prompt through the model, filling the cache.

    tokens: [B,S] left-aligned, right-padded. ``lengths`` ([B] int32, default
    S) gives each prompt's true length: logits are read at position
    ``lengths-1`` and ``cache["len"]`` is set per sequence, so the
    continuous-batching engine can prefill padded buckets. Pad rows beyond a
    sequence's length hold garbage KV but are never attended (decode masks to
    cache len and overwrites them one position at a time).
    """
    b, s = tokens.shape
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    positions = jnp.arange(s)[None, :]
    inv_freq = jnp.asarray(rope_frequencies(
        cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
        original_max_seq=cfg.max_seq,
    ))
    x = embed_tokens(params, tokens, cfg)

    def block(x, xs):
        lp, k_cache_l, v_cache_l = xs
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        if "wq_q" in lp:
            q = qmm("bsd,dhk->bshk", h, lp, "wq", cfg)
            k = qmm("bsd,dhk->bshk", h, lp, "wk", cfg)
            v = qmm("bsd,dhk->bshk", h, lp, "wv", cfg)
        else:
            q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"].astype(cfg.dtype))
            k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"].astype(cfg.dtype))
            v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"].astype(cfg.dtype))
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        # honor the configured impl ("ring"/"ulysses" are training-only
        # context-parallel paths; prefill falls back to the first-party
        # pallas kernel for those — O(S) memory, CPU-interpretable)
        impl = cfg.attn_impl if cfg.attn_impl in ("xla", "flash", "pallas") \
            else "pallas"
        o = attention(q, k, v, causal=True, impl=impl,
                      block_q=cfg.attn_block, block_kv=cfg.attn_block)
        if "wo_q" in lp:
            o = qmm("bshk,hkd->bsd", o, lp, "wo", cfg)
        else:
            o = jnp.einsum("bshk,hkd->bsd", o, lp["wo"].astype(cfg.dtype))
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        down, _ = _ffn(h, lp, cfg, token_mask=positions < lengths[:, None])
        x = x + down
        new_k = jax.lax.dynamic_update_slice(
            k_cache_l, k.astype(k_cache_l.dtype), (0, 0, 0, 0)
        )
        new_v = jax.lax.dynamic_update_slice(
            v_cache_l, v.astype(v_cache_l.dtype), (0, 0, 0, 0)
        )
        return x, (new_k, new_v)

    x, (new_k, new_v) = jax.lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(lengths - 1, 0)[:, None, None].astype(jnp.int32),
        axis=1,
    )[:, 0]
    if "embed_q" in params:
        logits = quant_head_logits(params, last, cfg)
    else:
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bd,dv->bv", last, head.astype(cfg.dtype))
    cache = {"k": new_k, "v": new_v, "len": lengths.astype(jnp.int32)}
    return logits.astype(jnp.float32), cache


def decode_step(params, token, cfg: LlamaConfig, cache):
    """One decode step. token: [B] int32 -> (logits [B,V], cache)."""
    b = token.shape[0]
    pos = cache["len"]  # [B]
    positions = pos[:, None]
    inv_freq = jnp.asarray(rope_frequencies(
        cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
        original_max_seq=cfg.max_seq,
    ))
    x = embed_tokens(params, token[:, None], cfg)

    def block(x, xs):
        lp, k_cache_l, v_cache_l = xs
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        if "wq_q" in lp:
            q = qmm("bsd,dhk->bshk", h, lp, "wq", cfg)
            k = qmm("bsd,dhk->bshk", h, lp, "wk", cfg)
            v = qmm("bsd,dhk->bshk", h, lp, "wv", cfg)
        else:
            q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"].astype(cfg.dtype))
            k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"].astype(cfg.dtype))
            v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"].astype(cfg.dtype))
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        # scatter the new KV row at each sequence's current length
        idx = pos[:, None, None, None]
        onehot = (jnp.arange(k_cache_l.shape[1])[None, :, None, None] == idx)
        new_k = jnp.where(onehot, k.astype(k_cache_l.dtype), k_cache_l)
        new_v = jnp.where(onehot, v.astype(v_cache_l.dtype), v_cache_l)
        o = decode_attention(q, new_k, new_v, pos + 1)
        if "wo_q" in lp:
            o = qmm("bshk,hkd->bsd", o, lp, "wo", cfg)
        else:
            o = jnp.einsum("bshk,hkd->bsd", o, lp["wo"].astype(cfg.dtype))
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        down, _ = _ffn(h, lp, cfg, token_mask=(pos > 0)[:, None])
        x = x + down
        return x, (new_k, new_v)

    x, (new_k, new_v) = jax.lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if "embed_q" in params:
        logits = quant_head_logits(params, x[:, 0], cfg)
    else:
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = jnp.einsum("bd,dv->bv", x[:, 0], head.astype(cfg.dtype))
    return logits.astype(jnp.float32), {
        "k": new_k, "v": new_v, "len": cache["len"] + 1
    }
