from kubeflow_tpu.models import llama, mnist, resnet
