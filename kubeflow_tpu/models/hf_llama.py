"""HF-layout Llama checkpoint loader/saver (safetensors <-> param pytree).

Parity: SURVEY.md §2.4 'Runtime servers' — the reference's
huggingfaceserver loads HF-hub-layout checkpoints (config.json +
model*.safetensors [+ index] + tokenizer.json) straight into its runtime
([U] kserve:python/huggingfaceserver). This module is the TPU-native
equivalent: it maps the HF Llama tensor layout onto this repo's
scan-stacked pytree (models/llama.py) with

- torch Linear [out, in] -> einsum [in, out] transposition, and head-dim
  splitting for the attention projections;
- per-tensor lazy reads (safetensors mmap) so peak host memory is one
  tensor, not the whole checkpoint;
- dtype casting at load (bf16 params by default for serving);
- optional *sharded* materialization: given a Mesh, every param is
  device_put with the NamedSharding derived from
  llama.param_logical_axes — so an 8B/70B checkpoint is never resident
  unsharded on one device.

The RoPE convention matches: HF Llama uses the rotate-half (split-half)
layout, exactly what ops/rotary.py implements, so no weight permutation is
needed.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models import llama
from kubeflow_tpu.parallel import sharding as shd

try:  # safetensors ships with transformers in this environment
    from safetensors import safe_open
    from safetensors.flax import save_file as _st_save
except ImportError:  # pragma: no cover - env always has it; keep import soft
    safe_open = None
    _st_save = None


# ---------------------------------------------------------------------------
# config.json <-> LlamaConfig
# ---------------------------------------------------------------------------

def config_from_hf(d: dict[str, Any], **overrides) -> llama.LlamaConfig:
    """Translate an HF LlamaConfig dict into this repo's LlamaConfig."""
    rope_scaling = d.get("rope_scaling") or {}
    scaling_type = rope_scaling.get("rope_type") or rope_scaling.get("type")
    kw: dict[str, Any] = dict(
        vocab_size=d["vocab_size"],
        dim=d["hidden_size"],
        n_layers=d["num_hidden_layers"],
        n_heads=d["num_attention_heads"],
        n_kv_heads=d.get("num_key_value_heads", d["num_attention_heads"]),
        mlp_dim=d["intermediate_size"],
        max_seq=d.get("max_position_embeddings", 8192),
        rope_theta=float(d.get("rope_theta", 500000.0)),
        rope_scaling="llama3" if scaling_type == "llama3" else None,
        norm_eps=float(d.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(d.get("tie_word_embeddings", False)),
    )
    if d.get("num_local_experts"):
        # Mixtral layout: routed FFN mixture, attention unchanged
        kw["n_experts"] = int(d["num_local_experts"])
        kw["moe_top_k"] = int(d.get("num_experts_per_tok", 2))
    kw.update(overrides)
    return llama.LlamaConfig(**kw)


def config_to_hf(cfg: llama.LlamaConfig) -> dict[str, Any]:
    d: dict[str, Any] = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.mlp_dim,
        "max_position_embeddings": cfg.max_seq,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "bfloat16",
    }
    if cfg.rope_scaling == "llama3":
        d["rope_scaling"] = {
            "rope_type": "llama3", "factor": 8.0,
            "low_freq_factor": 1.0, "high_freq_factor": 4.0,
            "original_max_position_embeddings": cfg.max_seq,
        }
    if cfg.n_experts:
        d["architectures"] = ["MixtralForCausalLM"]
        d["model_type"] = "mixtral"
        d["num_local_experts"] = cfg.n_experts
        d["num_experts_per_tok"] = cfg.moe_top_k
    return d


def load_config(model_dir: str, **overrides) -> llama.LlamaConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        return config_from_hf(json.load(f), **overrides)


# ---------------------------------------------------------------------------
# weight-name mapping
# ---------------------------------------------------------------------------

class _TensorIndex:
    """name -> (file, lazy reader) over model.safetensors or the sharded
    model-0000x-of-0000y.safetensors + model.safetensors.index.json form."""

    def __init__(self, model_dir: str):
        if safe_open is None:  # pragma: no cover
            raise RuntimeError("safetensors is required to load HF checkpoints")
        self.model_dir = model_dir
        index_path = os.path.join(model_dir, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path) as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            self._files = sorted(set(weight_map.values()))
            self._where = weight_map
        else:
            single = os.path.join(model_dir, "model.safetensors")
            if not os.path.exists(single):
                raise FileNotFoundError(
                    f"no model.safetensors[.index.json] in {model_dir}")
            self._files = ["model.safetensors"]
            self._where = None
        self._open: dict[str, Any] = {}

    def _handle(self, fname: str):
        if fname not in self._open:
            self._open[fname] = safe_open(
                os.path.join(self.model_dir, fname), framework="flax")
        return self._open[fname]

    def names(self) -> set[str]:
        if self._where is not None:
            return set(self._where)
        return set(self._handle(self._files[0]).keys())

    def get(self, name: str) -> jax.Array:
        fname = self._where[name] if self._where else self._files[0]
        return self._handle(fname).get_tensor(name)

    def close(self) -> None:
        self._open.clear()


def _linear(w: jax.Array) -> jax.Array:
    """torch Linear weight [out, in] -> einsum layout [in, out]."""
    return w.T


def load_params(model_dir: str, cfg: Optional[llama.LlamaConfig] = None, *,
                dtype=jnp.bfloat16, mesh=None, rules=None):
    """Read an HF-layout Llama checkpoint into the scan-stacked pytree.

    With ``mesh``, each param is placed with the NamedSharding from
    llama.param_logical_axes + the rule table — the sharded-load path, so
    nothing bigger than one tensor is ever host-resident and nothing bigger
    than its shard is device-resident per chip.
    """
    cfg = cfg or load_config(model_dir, dtype=dtype)
    idx = _TensorIndex(model_dir)
    h, kv, hd, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.dim

    put = _placer(cfg, mesh, rules, dtype)
    stack_shardings = None
    if mesh is not None:
        stack_shardings = shd.tree_shardings(
            mesh, llama.param_logical_axes(cfg), rules)["layers"]

    def layer_stack(fmt: str, transform, key: str = "") -> jax.Array:
        """Stack per-layer tensors. With a mesh, the stack materializes
        SHARD BY SHARD (jax.make_array_from_callback reading one layer
        tensor at a time) — a 70B/Mixtral FFN stack never exists as one
        host allocation; without a mesh, plain host stacking."""
        if stack_shardings is None:
            return jnp.stack([transform(idx.get(fmt.format(i)))
                              for i in range(cfg.n_layers)])
        sample = np.asarray(transform(idx.get(fmt.format(0))))
        gshape = (cfg.n_layers,) + sample.shape

        def cb(index):
            li = index[0]
            return np.stack([
                np.asarray(transform(idx.get(fmt.format(i))))[
                    tuple(index[1:])].astype(dtype)
                for i in range(*li.indices(cfg.n_layers))])

        return jax.make_array_from_callback(
            gshape, stack_shardings[key], cb)

    layers = {
        "attn_norm": layer_stack(
            "model.layers.{}.input_layernorm.weight", lambda w: w,
            "attn_norm"),
        "mlp_norm": layer_stack(
            "model.layers.{}.post_attention_layernorm.weight", lambda w: w,
            "mlp_norm"),
        "wq": layer_stack(
            "model.layers.{}.self_attn.q_proj.weight",
            lambda w: _linear(w).reshape(d, h, hd), "wq"),
        "wk": layer_stack(
            "model.layers.{}.self_attn.k_proj.weight",
            lambda w: _linear(w).reshape(d, kv, hd), "wk"),
        "wv": layer_stack(
            "model.layers.{}.self_attn.v_proj.weight",
            lambda w: _linear(w).reshape(d, kv, hd), "wv"),
        "wo": layer_stack(
            "model.layers.{}.self_attn.o_proj.weight",
            lambda w: _linear(w).reshape(h, hd, d), "wo"),
    }
    if cfg.n_experts:
        # Mixtral block_sparse_moe: router gate [E, d] -> [d, E]; per-expert
        # w1(gate)/w3(up) [m, d] -> [d, m]; w2(down) [d, m] -> [m, d];
        # experts stack on a leading E dim matching llama.init_params
        E = cfg.n_experts

        def expert_stack(fmt: str, key: str) -> jax.Array:
            if stack_shardings is None:
                return jnp.stack([
                    jnp.stack([_linear(idx.get(fmt.format(i, e)))
                               for e in range(E)])
                    for i in range(cfg.n_layers)])
            sample = np.asarray(_linear(idx.get(fmt.format(0, 0))))
            gshape = (cfg.n_layers, E) + sample.shape

            def cb(index):
                li, ei = index[0], index[1]
                return np.stack([
                    np.stack([
                        np.asarray(_linear(idx.get(fmt.format(i, e))))[
                            tuple(index[2:])].astype(dtype)
                        for e in range(*ei.indices(E))])
                    for i in range(*li.indices(cfg.n_layers))])

            return jax.make_array_from_callback(
                gshape, stack_shardings[key], cb)

        layers["moe_router"] = layer_stack(
            "model.layers.{}.block_sparse_moe.gate.weight", _linear,
            "moe_router")
        layers["w_gate"] = expert_stack(
            "model.layers.{}.block_sparse_moe.experts.{}.w1.weight",
            "w_gate")
        layers["w_up"] = expert_stack(
            "model.layers.{}.block_sparse_moe.experts.{}.w3.weight",
            "w_up")
        layers["w_down"] = expert_stack(
            "model.layers.{}.block_sparse_moe.experts.{}.w2.weight",
            "w_down")
    else:
        layers["w_gate"] = layer_stack(
            "model.layers.{}.mlp.gate_proj.weight", _linear, "w_gate")
        layers["w_up"] = layer_stack(
            "model.layers.{}.mlp.up_proj.weight", _linear, "w_up")
        layers["w_down"] = layer_stack(
            "model.layers.{}.mlp.down_proj.weight", _linear, "w_down")
    params = {
        "embed": idx.get("model.embed_tokens.weight"),
        "layers": layers,
        "final_norm": idx.get("model.norm.weight"),
    }
    if not cfg.tie_embeddings:
        name = ("lm_head.weight" if "lm_head.weight" in idx.names()
                else "model.embed_tokens.weight")
        params["lm_head"] = _linear(idx.get(name))
    params = put(params)
    idx.close()
    return cfg, params


def _placer(cfg, mesh, rules, dtype):
    axes = llama.param_logical_axes(cfg)

    def put(params):
        if mesh is None:
            return jax.tree.map(lambda x: jnp.asarray(x, dtype), params)
        shardings = shd.tree_shardings(mesh, axes, rules)
        return jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, dtype), s),
            params, shardings)

    return put


def save_pretrained(model_dir: str, cfg: llama.LlamaConfig, params) -> None:
    """Write the pytree back out in HF layout (config.json +
    model.safetensors) — the export path, and the fixture-maker for tests."""
    if _st_save is None:  # pragma: no cover
        raise RuntimeError("safetensors is required to save HF checkpoints")
    os.makedirs(model_dir, exist_ok=True)
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(config_to_hf(cfg), f, indent=1)
    h, kv, hd, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.dim
    lp = params["layers"]
    flat: dict[str, jax.Array] = {
        "model.embed_tokens.weight": params["embed"],
        "model.norm.weight": params["final_norm"],
    }
    if not cfg.tie_embeddings:
        flat["lm_head.weight"] = params["lm_head"].T
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        flat[p + "input_layernorm.weight"] = lp["attn_norm"][i]
        flat[p + "post_attention_layernorm.weight"] = lp["mlp_norm"][i]
        flat[p + "self_attn.q_proj.weight"] = lp["wq"][i].reshape(d, h * hd).T
        flat[p + "self_attn.k_proj.weight"] = lp["wk"][i].reshape(d, kv * hd).T
        flat[p + "self_attn.v_proj.weight"] = lp["wv"][i].reshape(d, kv * hd).T
        flat[p + "self_attn.o_proj.weight"] = lp["wo"][i].reshape(h * hd, d).T
        if cfg.n_experts:
            flat[p + "block_sparse_moe.gate.weight"] = lp["moe_router"][i].T
            for e in range(cfg.n_experts):
                ep = p + f"block_sparse_moe.experts.{e}."
                flat[ep + "w1.weight"] = lp["w_gate"][i, e].T
                flat[ep + "w3.weight"] = lp["w_up"][i, e].T
                flat[ep + "w2.weight"] = lp["w_down"][i, e].T
        else:
            flat[p + "mlp.gate_proj.weight"] = lp["w_gate"][i].T
            flat[p + "mlp.up_proj.weight"] = lp["w_up"][i].T
            flat[p + "mlp.down_proj.weight"] = lp["w_down"][i].T
    flat = {k: jnp.asarray(v) for k, v in flat.items()}
    _st_save(flat, os.path.join(model_dir, "model.safetensors"))


def load_pretrained(model_dir: str, *, dtype=jnp.bfloat16, mesh=None,
                    rules=None, **config_overrides):
    """One call: (LlamaConfig, params) from an HF checkpoint directory.
    The param ``dtype`` doubles as the config's compute dtype unless a
    ``dtype`` config override says otherwise."""
    config_overrides.setdefault("dtype", dtype)
    cfg = load_config(model_dir, **config_overrides)
    return load_params(model_dir, cfg, dtype=dtype, mesh=mesh, rules=rules)
