"""Pre-imported worker zygote: fork()-spawned pods skip cold imports.

Submit→first-step latency (north-star #2, BASELINE.md row 2) is dominated
on CPU workers by each pod paying a fresh interpreter + ``import jax`` +
framework imports before rendezvous even starts. The zygote is the
forkserver answer (the same trick CPython's ``multiprocessing``
forkserver and Ray's worker pool use): one helper process imports the
heavy modules ONCE — crucially, importing jax does NOT initialize any
backend, so the fork inherits warm code with no device state — then forks
a child per pod in ~milliseconds.

Protocol (one connection per pod, held open for its life):
  daemon -> zygote: one JSON line {"argv": [...], "env": {...}, "log": p}
  zygote -> daemon: {"pid": N}            after the fork
  zygote -> daemon: {"exit": code}        when the child exits

The child applies the pod env (backends are uninitialized, so XLA_FLAGS /
JAX_PLATFORMS / KFT_FORCE_PLATFORM all still take effect), points
stdout/stderr at the pod log (omitting ``log`` inherits the zygote's own
stdout — the pod log, for the in-pod kube form), and runs ``argv`` —
which must be the ``[sys.executable, "-m", module, *args]`` form
(anything else is the daemon's cue to fall back to a plain spawn).

Two listener forms behind one serve():

- a unix socket path — ``LocalProcessCluster(warm_pool=True)`` owns one
  zygote per daemon and routes eligible pods through it;
- ``tcp://host:port`` (port 0 = ephemeral) — the NODE-RESIDENT form: a
  pre-warmed standby pod on the Kube backend runs this as its main
  command, and the WarmPoolController claims the pod and delivers the
  worker argv over the pod network (controller/warmpool.py). The bound
  address is announced via ``--announce-file`` (and the
  KFT_ZYGOTE_ANNOUNCE env the kubelet injects) so the node agent can
  publish it as a pod annotation.

SECURITY (tcp form): a fork server reachable over the pod network is an
arbitrary-code-execution endpoint, so it is token-fenced — when
``KFT_ZYGOTE_TOKEN`` is set (the WarmPoolController stamps a random one
into every standby pod's env), a request whose ``"token"`` field does not
match is refused before any fork. The token lives in the pod spec, i.e.
the same trust domain as the pod's ServiceAccount: reading it requires
apiserver pod-read rights, which already imply claim rights. Deployments
should ALSO scope a NetworkPolicy to the operator, defense in depth.

RECLAIM (the warm-pool return arc): an early-stopped trial's pod goes
BACK to the pool instead of being deleted. The controller sends
``{"reclaim": true, "token": <current>, "new_token": <fresh>}``: the
zygote SIGKILLs the live forked worker's process group (the child called
setsid, so its pgid is its pid), ROTATES the accepted token, and acks
``{"reclaimed": true, "killed": [...]}``. Token rotation is the fence
that makes the returned pod safe to re-claim: a stale claimant replaying
the old token — e.g. a late exec from the trial that was just stopped —
is refused before any fork. The accept loop survives worker death, so
the same resident zygote serves the next claim with imports still warm.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading

# fork() while sibling handler threads are mid-malloc/mid-lock is the
# classic threaded-fork deadlock: the child inherits a heap/lock snapshot
# whose owners don't exist there. Serializing forks doesn't remove that
# hazard entirely (accept loops and CPython runtime threads still exist),
# but it guarantees no two handler threads interleave fork bookkeeping,
# which is where the observed wedges live. Held only around os.fork()
# itself — waitpid runs unlocked so forks never serialize on pod LIFETIME.
_fork_lock = threading.Lock()


def _preimport() -> None:
    """The heavy import set a training worker OR serving replica pays
    cold. Serving joined in the fleet round: a warm-pool scale-up forks
    the predictor runtime from this zygote, so its module tree must be
    resident too (none of it initializes a backend — asserted below)."""
    import jax  # noqa: F401
    import jax.numpy  # noqa: F401
    import numpy  # noqa: F401
    import optax  # noqa: F401

    from kubeflow_tpu import models, serving, training  # noqa: F401
    from kubeflow_tpu.rendezvous import bootstrap  # noqa: F401
    from kubeflow_tpu.serving import runtime  # noqa: F401

    # invariant the whole design rests on: imports must not have touched a
    # backend (a forked live TPU/CPU client would be corrupt)
    from jax._src import xla_bridge

    assert not xla_bridge._backends, "zygote initialized a JAX backend"


def _run_child(req: dict) -> None:
    """In the forked child: become the pod process."""
    os.setsid()                              # own signal group, like Popen
    try:
        # die with the zygote: a killed zygote must not leave orphaned
        # workers holding devices (PR_SET_PDEATHSIG=1; the handler thread
        # that forked us lives in waitpid until we exit, so the Linux
        # thread-death caveat cannot fire early)
        import ctypes

        ctypes.CDLL(None, use_errno=True).prctl(1, 9, 0, 0, 0)
    except Exception:
        pass
    argv = req["argv"]
    env = req.get("env") or {}
    os.environ.update({k: str(v) for k, v in env.items()})
    if req.get("log"):
        fd = os.open(req["log"],
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        os.close(fd)
    # no "log": inherit the zygote's own stdout/stderr — in the standby-pod
    # form that IS the pod log, which is where the worker should write
    if os.environ.get("KFT_FORCE_PLATFORM"):
        import jax

        jax.config.update("jax_platforms",
                          os.environ["KFT_FORCE_PLATFORM"])
    # [python, -m, module, *args] — validated by the daemon before routing
    module = argv[2]
    sys.argv = [argv[0]] + argv[3:]
    import runpy

    runpy.run_module(module, run_name="__main__", alter_sys=True)


def serve(listen: str, announce_file: str | None = None) -> int:
    _preimport()
    if listen.startswith("tcp://"):
        host, _, port = listen[len("tcp://"):].rpartition(":")
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port or 0)))
        bound = f"{srv.getsockname()[0]}:{srv.getsockname()[1]}"
    else:
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(listen)
        except FileNotFoundError:
            pass
        srv.bind(listen)
        bound = listen
    srv.listen(64)
    if announce_file:
        # atomic announce: the node agent polls for this file and publishes
        # the address as a pod annotation (a partially written file must
        # never be read as an address)
        tmp = f"{announce_file}.tmp"
        with open(tmp, "w") as f:
            f.write(bound)
        os.replace(tmp, announce_file)
    print(f"zygote ready on {bound}", flush=True)

    # the accepted token is MUTABLE state (reclaim rotates it) and the
    # forked-worker pids are tracked so a reclaim can kill them — both
    # shared across handler threads behind one lock
    state = {"token": os.environ.get("KFT_ZYGOTE_TOKEN", "")}
    live_pids: set = set()
    state_lock = threading.Lock()

    def handle(conn: socket.socket) -> None:
        try:
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
            req = json.loads(buf)
            with state_lock:
                token = state["token"]
            if token and req.get("token") != token:
                # unauthenticated peer on the pod network — or a STALE
                # claimant replaying a pre-reclaim token: refuse BEFORE
                # any fork (see module docstring, SECURITY / RECLAIM)
                conn.sendall(json.dumps(
                    {"error": "bad token"}).encode() + b"\n")
                return
            if req.get("reclaim"):
                # warm-pool return arc: kill the live worker's process
                # group and rotate the token BEFORE acking, so by the
                # time the pod shows standby again the old trial cannot
                # fork and the old token cannot exec
                import signal

                with state_lock:
                    doomed = list(live_pids)
                    if req.get("new_token"):
                        state["token"] = str(req["new_token"])
                killed = []
                for pid in doomed:
                    try:
                        os.killpg(pid, signal.SIGKILL)
                        killed.append(pid)
                    except (ProcessLookupError, PermissionError):
                        pass        # already gone: reclaim is idempotent
                conn.sendall(json.dumps(
                    {"reclaimed": True, "killed": killed}
                ).encode() + b"\n")
                return
            with _fork_lock:
                pid = os.fork()
            if pid == 0:
                try:
                    srv.close()
                    conn.close()
                    _run_child(req)
                    os._exit(0)
                except SystemExit as e:
                    # CPython semantics: int -> that code; None -> 0;
                    # anything else (sys.exit("message")) -> stderr + 1
                    if e.code is None:
                        os._exit(0)
                    if isinstance(e.code, int):
                        os._exit(e.code)
                    print(e.code, file=sys.stderr)
                    os._exit(1)
                except BaseException:
                    import traceback

                    traceback.print_exc()
                    os._exit(1)
            with state_lock:
                live_pids.add(pid)
            conn.sendall(json.dumps({"pid": pid}).encode() + b"\n")
            _, status = os.waitpid(pid, 0)
            with state_lock:
                live_pids.discard(pid)
            code = os.waitstatus_to_exitcode(status)
            try:
                conn.sendall(json.dumps({"exit": code}).encode() + b"\n")
            except OSError:
                pass                        # daemon gone; child is reaped
        finally:
            conn.close()

    while True:
        conn, _ = srv.accept()
        threading.Thread(target=handle, args=(conn,), daemon=True).start()


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    announce = None
    if "--announce-file" in args:
        i = args.index("--announce-file")
        try:
            announce = args[i + 1]
        except IndexError:
            print("--announce-file needs a path", file=sys.stderr)
            return 2
        del args[i:i + 2]
    # the kubelet-injected announce convention: a node agent that spawns
    # this pod sets KFT_ZYGOTE_ANNOUNCE so it can learn the bound address
    # without rewriting the pod command
    if announce is None:
        announce = os.environ.get("KFT_ZYGOTE_ANNOUNCE") or None
    if len(args) != 1:
        print("usage: python -m kubeflow_tpu.rendezvous.zygote "
              "<socket-path | tcp://host:port> [--announce-file PATH]",
              file=sys.stderr)
        return 2
    return serve(args[0], announce_file=announce)


if __name__ == "__main__":
    sys.exit(main())
