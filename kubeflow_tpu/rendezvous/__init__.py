from kubeflow_tpu.rendezvous.bootstrap import WorldInfo, initialize, world_from_env
