"""Smoke workload for JAXJob e2e: rendezvous + a cross-process collective.

Run as ``python -m kubeflow_tpu.rendezvous.worker_check`` inside a pod. Reads
the operator env contract, initializes the distributed world, verifies the
global device count, runs a psum across the whole world, and writes metrics.
Exit 0 = healthy world. This is the 'MNIST-class CPU stand-in image' role
from the reference's e2e strategy (SURVEY.md §4.3).
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    import jax

    if os.environ.get("KFT_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_FORCE_PLATFORM"])

    import jax.numpy as jnp

    from kubeflow_tpu.rendezvous.bootstrap import initialize
    from kubeflow_tpu.training.metrics import MetricsWriter

    world, mesh = initialize()
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    expected = world.num_processes * n_local
    assert n_global == expected, f"device_count {n_global} != {expected}"

    # cross-process collective: global mean over a data-sharded array
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(("data", "fsdp")))
    import numpy as np

    local = np.full((n_local, 4), float(world.process_id), np.float32)
    arr = jax.make_array_from_process_local_data(sharding, local)
    total = float(jax.jit(jnp.sum)(arr))
    expect_total = 4 * n_local * sum(range(world.num_processes))
    assert abs(total - expect_total) < 1e-5, f"psum {total} != {expect_total}"

    metrics_path = os.environ.get("KFT_METRICS_PATH")
    if metrics_path:
        MetricsWriter(metrics_path).write(
            0, world_ok=1.0, process_id=world.process_id, total=total
        )
    print(f"worker {world.process_id}/{world.num_processes}: world ok, "
          f"devices={n_global}, collective={total}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
