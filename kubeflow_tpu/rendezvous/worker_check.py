"""Smoke workload for JAXJob e2e: rendezvous + a cross-process collective.

Run as ``python -m kubeflow_tpu.rendezvous.worker_check`` inside a pod. Reads
the operator env contract, initializes the distributed world, verifies the
global device count, runs a psum across the whole world, and writes metrics.
Exit 0 = healthy world. This is the 'MNIST-class CPU stand-in image' role
from the reference's e2e strategy (SURVEY.md §4.3).
"""

from __future__ import annotations

import os
import sys


def _phase(phases: dict, name: str) -> None:
    """Record a named absolute timestamp; flushed to KFT_PHASES_PATH so the
    operator/bench can decompose submit->first-step into pod spawn /
    imports / rendezvous / compile+step (BASELINE.md row 2).

    Two transports behind the one env value, mirroring KFT_HEARTBEAT_FILE:
    a filesystem path (shared-fs backends) writes an atomic JSON file; an
    http(s) URL (kube backend — the operator injects its heartbeat route)
    POSTs {"phases": {...}} to the operator, which folds it into
    ``Operator.phase_reports``. Whole-dict posts each time: delivery is
    at-least-once and the receiver merges, so a lost or reordered POST
    costs one stamp's latency, never the decomposition."""
    import time

    phases[name] = time.time()
    path = os.environ.get("KFT_PHASES_PATH")
    if not path:
        return
    import json

    if path.startswith(("http://", "https://")):
        import urllib.request

        try:
            req = urllib.request.Request(
                path, method="POST",
                data=json.dumps({"phases": phases}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=5).close()
        except Exception:
            pass        # like heartbeats: missed posts ARE the signal
        return
    try:
        with open(f"{path}.{os.getpid()}", "w") as f:
            json.dump(phases, f)
        os.replace(f"{path}.{os.getpid()}",
                   f"{path}.{os.environ.get('KFT_PROCESS_ID', '0')}")
    except OSError:
        pass


def main() -> int:
    phases: dict = {}
    _phase(phases, "proc_start")
    import jax

    if os.environ.get("KFT_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_FORCE_PLATFORM"])

    import jax.numpy as jnp

    from kubeflow_tpu.rendezvous.bootstrap import initialize
    from kubeflow_tpu.training.metrics import MetricsWriter

    _phase(phases, "imports_done")
    world, mesh = initialize()
    _phase(phases, "rendezvous_done")
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    expected = world.num_processes * n_local
    assert n_global == expected, f"device_count {n_global} != {expected}"

    # cross-process collective: global mean over a data-sharded array
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(("data", "fsdp")))
    import numpy as np

    local = np.full((n_local, 4), float(world.process_id), np.float32)
    arr = jax.make_array_from_process_local_data(sharding, local)
    total = float(jax.jit(jnp.sum)(arr))
    expect_total = 4 * n_local * sum(range(world.num_processes))
    assert abs(total - expect_total) < 1e-5, f"psum {total} != {expect_total}"

    metrics_path = os.environ.get("KFT_METRICS_PATH")
    if metrics_path:
        MetricsWriter(metrics_path).write(
            0, world_ok=1.0, process_id=world.process_id, total=total
        )

    # optional real-training mode: KFT_TRAIN_STEPS makes this the
    # 'tiny CPU training image' of the operator e2e — an actual fit() on the
    # world mesh, so heartbeats/first-step latency come from real steps
    steps = int(os.environ.get("KFT_TRAIN_STEPS", "0"))
    if steps:
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama
        from kubeflow_tpu.training import (
            Trainer, TrainerConfig, lm_loss_fn, put_batch,
            synthetic_lm_batches,
        )
        from kubeflow_tpu.training.loop import fit

        cfg = llama.llama_tiny(dtype=jnp.float32)
        trainer = Trainer(
            mesh=mesh,
            init_params_fn=lambda r: llama.init_params(r, cfg),
            params_logical_axes=llama.param_logical_axes(cfg),
            loss_fn=lm_loss_fn(llama.forward, cfg),
            config=TrainerConfig(learning_rate=1e-3, warmup_steps=2,
                                 total_steps=max(steps, 3)),
        )
        global_batch = max(2 * world.num_processes, 4)

        def batches(start):
            return (put_batch(mesh, b) for b in synthetic_lm_batches(
                cfg.vocab_size, global_batch, 16, start_step=start))

        metrics = MetricsWriter(metrics_path) if metrics_path else None

        def _first_step(step, m):
            if "first_step_done" not in phases:
                _phase(phases, "first_step_done")

        result = fit(trainer, batches, rng=jax.random.key(0),
                     max_steps=steps, metrics=metrics, metrics_every=1,
                     checkpoint_dir=os.environ.get("KFT_CHECKPOINT_DIR"),
                     on_step=_first_step)
        print(f"worker {world.process_id}: trained to step "
              f"{result.final_step} (resumed_from={result.resumed_from})")

    print(f"worker {world.process_id}/{world.num_processes}: world ok, "
          f"devices={n_global}, collective={total}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
