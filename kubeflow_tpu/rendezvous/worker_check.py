"""Smoke workload for JAXJob e2e: rendezvous + a cross-process collective.

Run as ``python -m kubeflow_tpu.rendezvous.worker_check`` inside a pod. Reads
the operator env contract, initializes the distributed world, verifies the
global device count, runs a psum across the whole world, and writes metrics.
Exit 0 = healthy world. This is the 'MNIST-class CPU stand-in image' role
from the reference's e2e strategy (SURVEY.md §4.3).
"""

from __future__ import annotations

import os
import sys


def _phase(phases: dict, name: str, extra: dict | None = None,
           at: float | None = None) -> None:
    """Record a named absolute timestamp (``at`` overrides "now" for
    events measured elsewhere, e.g. the profiler window's stop time);
    flushed to KFT_PHASES_PATH so the operator/bench can decompose
    submit->first-step into pod spawn / imports / rendezvous / compile /
    step 1 (BASELINE.md row 2).

    Two transports behind the one env value, mirroring KFT_HEARTBEAT_FILE:
    a filesystem path (shared-fs backends) writes an atomic JSON file; an
    http(s) URL (kube backend — the operator injects its heartbeat route)
    POSTs {"phases": {...}} to the operator, which folds it into
    ``Operator.phase_reports``. Whole-dict posts each time: delivery is
    at-least-once and the receiver merges, so a lost or reordered POST
    costs one stamp's latency, never the decomposition.

    ``extra`` rides the same POST body (e.g. {"depot": counters} — the
    operator folds it into kft_depot_* metrics); on the file transport
    each extra key lands in its own ``{path}.{key}.{process}`` file."""
    import time

    phases[name] = time.time() if at is None else float(at)
    path = os.environ.get("KFT_PHASES_PATH")
    if not path:
        return
    import json

    proc = os.environ.get("KFT_PROCESS_ID", "0")
    if path.startswith(("http://", "https://")):
        import urllib.request

        try:
            req = urllib.request.Request(
                path, method="POST",
                data=json.dumps(
                    {"phases": phases, **(extra or {})}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=5).close()
        except Exception:
            pass        # like heartbeats: missed posts ARE the signal
        return
    try:
        with open(f"{path}.{os.getpid()}", "w") as f:
            json.dump(phases, f)
        os.replace(f"{path}.{os.getpid()}", f"{path}.{proc}")
        for key, val in (extra or {}).items():
            with open(f"{path}.{key}.{os.getpid()}", "w") as f:
                json.dump(val, f)
            os.replace(f"{path}.{key}.{os.getpid()}",
                       f"{path}.{key}.{proc}")
    except OSError:
        pass


def main() -> int:
    phases: dict = {}
    _phase(phases, "proc_start")
    import jax

    if os.environ.get("KFT_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_FORCE_PLATFORM"])

    import jax.numpy as jnp

    from kubeflow_tpu.rendezvous.bootstrap import initialize
    from kubeflow_tpu.training.metrics import MetricsWriter

    _phase(phases, "imports_done")
    world, mesh = initialize()
    _phase(phases, "rendezvous_done")
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    expected = world.num_processes * n_local
    assert n_global == expected, f"device_count {n_global} != {expected}"

    # cross-process collective: global mean over a data-sharded array
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(("data", "fsdp")))
    import numpy as np

    local = np.full((n_local, 4), float(world.process_id), np.float32)
    arr = jax.make_array_from_process_local_data(sharding, local)
    total = float(jax.jit(jnp.sum)(arr))
    expect_total = 4 * n_local * sum(range(world.num_processes))
    assert abs(total - expect_total) < 1e-5, f"psum {total} != {expect_total}"

    metrics_path = os.environ.get("KFT_METRICS_PATH")
    if metrics_path:
        MetricsWriter(metrics_path).write(
            0, world_ok=1.0, process_id=world.process_id, total=total
        )

    # optional real-training mode: KFT_TRAIN_STEPS makes this the
    # 'tiny CPU training image' of the operator e2e — an actual fit() on the
    # world mesh, so heartbeats/first-step latency come from real steps
    steps = int(os.environ.get("KFT_TRAIN_STEPS", "0"))
    if steps:
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama
        from kubeflow_tpu.training import (
            Trainer, TrainerConfig, lm_loss_fn, put_batch,
            synthetic_lm_batches,
        )
        from kubeflow_tpu.training.loop import fit

        cfg = llama.llama_tiny(dtype=jnp.float32)
        trainer = Trainer(
            mesh=mesh,
            init_params_fn=lambda r: llama.init_params(r, cfg),
            params_logical_axes=llama.param_logical_axes(cfg),
            loss_fn=lm_loss_fn(llama.forward, cfg),
            config=TrainerConfig(learning_rate=1e-3, warmup_steps=2,
                                 total_steps=max(steps, 3)),
        )
        global_batch = max(2 * world.num_processes, 4)

        def batches(start):
            return (put_batch(mesh, b) for b in synthetic_lm_batches(
                cfg.vocab_size, global_batch, 16, start_step=start))

        # compile split from step 1 (the executable-depot fast path):
        # fetch the gang's train-step executable from the depot — or
        # compile and publish it — BEFORE fit, and stamp compile_done so
        # the submit→first-step decomposition separates compile from the
        # first real step. Followers (process_id > 0) wait for the
        # coordinator's publish instead of racing it with an identical
        # compile; every depot fallback is a counted local compile.
        from kubeflow_tpu.parallel.depot import DepotStats
        from kubeflow_tpu.rendezvous.bootstrap import depot_from_env

        dstats = DepotStats()
        try:
            depot = depot_from_env(stats=dstats)
        except Exception:
            # fail-open like every depot path: an unwritable KFT_DEPOT /
            # KFT_DEPOT_CACHE dir (read-only mount, deleted path) must
            # cost the fast path, never the job
            dstats.inc("fetch_errors")
            depot = None
        wait_s = (float(os.environ.get("KFT_DEPOT_WAIT_S", "120"))
                  if depot is not None and not world.is_coordinator
                  else 0.0)
        trainer.init_state(jax.random.key(0))
        # state_init_done..compile_done isolates the train-step
        # lower+compile (the depot-amortizable part) from the param/opt
        # init compiles and jit setup that precede it — without this
        # stamp a depot hit still looks compile-bound from outside
        _phase(phases, "state_init_done")

        # restart-aware resume handshake (elastic recovery): restore the
        # latest checkpoint BEFORE loading the compiled executable. A
        # replacement worker thus knows the exact step it takes over at
        # up front — and the ordering matters mechanically: a zygote-
        # forked child that deserializes the depot executable and THEN
        # runs the tensorstore restore corrupts its forked heap (observed
        # as SIGABRT/SIGSEGV after the first post-resume step); restore-
        # then-deserialize is stable. fit() skips its own restore via
        # already_resumed.
        from kubeflow_tpu.training.checkpoint import CheckpointManager
        from kubeflow_tpu.training.loop import restore_latest

        ckpt_dir = os.environ.get("KFT_CHECKPOINT_DIR")
        resumed = None
        if ckpt_dir:
            mgr = CheckpointManager(
                ckpt_dir,
                mirror=os.environ.get("KFT_CHECKPOINT_MIRROR") or None)
            resumed = restore_latest(trainer, mgr)
            mgr.close()
            if resumed is not None:
                phases["resumed_from_step"] = float(resumed)
                _phase(phases, "restore_done")

        depot_outcome = trainer.precompile(
            next(batches(0)), depot=depot, stats=dstats, wait_s=wait_s)
        # non-timestamp stamp riding the same merge transport: the bench's
        # recovery decomposition needs the replacement's depot outcome
        # without scraping logs (1.0 = executable deserialized, no compile)
        phases["depot_hit"] = 1.0 if depot_outcome == "hit" else 0.0
        _phase(phases, "compile_done",
               extra={"depot": dstats.snapshot()} if depot is not None
               else None)

        metrics = MetricsWriter(metrics_path) if metrics_path else None
        # recovery-bench pacing: a tiny CPU model finishes all its steps
        # inside one chaos tick — an optional per-step sleep widens the
        # kill window without changing the math
        step_sleep = float(os.environ.get("KFT_STEP_SLEEP", "0"))

        def _first_step(step, m):
            if "first_step_done" not in phases:
                _phase(phases, "first_step_done")
            if step_sleep:
                import time as _time

                _time.sleep(step_sleep)

        result = fit(trainer, batches, rng=jax.random.key(0),
                     max_steps=steps, metrics=metrics, metrics_every=1,
                     checkpoint_dir=ckpt_dir,
                     checkpoint_every=int(
                         os.environ.get("KFT_CHECKPOINT_EVERY", "100")),
                     on_step=_first_step, already_resumed=resumed)
        # profiler artifact stamp: fit() honored KFT_PROFILE_DIR /
        # KFT_PROFILE_STEPS from the pod env (training/loop contract).
        # Stamped ONLY when the window actually ran (result.profile), at
        # the REAL start/stop wall times — the job-trace worker.profile
        # span must cover the profiled window, not end-of-training, and
        # a run that never reached the window must not report a phantom
        # artifact. The trace-dir path rides as a string stamp, so the
        # operator's job trace carries WHERE the profile landed as a
        # span attr — no log scraping.
        if result.profile is not None:
            phases["profile_dir"] = result.profile["dir"]
            phases["profile_start"] = result.profile["t_start"]
            _phase(phases, "profile_done", at=result.profile["t_stop"])
        incarnation = os.environ.get("KFT_WORKER_INCARNATION", "0")
        print(f"worker {world.process_id}: trained to step "
              f"{result.final_step} (resumed_from={result.resumed_from}, "
              f"depot={depot_outcome}, incarnation={incarnation})")

    print(f"worker {world.process_id}/{world.num_processes}: world ok, "
          f"devices={n_global}, collective={total}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
