"""In-worker bootstrap: operator-injected env -> initialized JAX world + mesh.

The worker-side half of the rendezvous contract (SURVEY.md §2.8): the
controller stamps KFT_COORDINATOR / KFT_NUM_PROCESSES / KFT_PROCESS_ID (+
KFT_MESH / KFT_DCN topology), and this module turns them into
`jax.distributed.initialize()` + a canonical device mesh. The TPU-native
replacement for torchrun/TF_CONFIG/MPI-hostfile bootstrap.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

# executable-depot env contract (KFT_DEPOT / KFT_DEPOT_TOKEN /
# KFT_DEPOT_CACHE): re-exported here because this module IS the
# worker-side env contract — workers resolve their depot next to the
# compile cache below. The depot goes further than the cache: it ships
# the COMPILED executable across nodes (compile-once at gang width N),
# where jax_compilation_cache_dir only helps processes sharing a disk.
from kubeflow_tpu.parallel.depot import depot_from_env  # noqa: F401


@dataclasses.dataclass
class WorldInfo:
    coordinator: str
    num_processes: int
    process_id: int
    job_name: str = ""

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def load_downward_env(path: str = "/etc/podinfo/annotations",
                      env: Optional[dict] = None) -> dict:
    """Fold late-bound pod annotations into the env contract.

    On a real cluster (controller/kube.py KubeCluster), values decided at
    gang admission — after the pod spec is immutable — travel as
    ``kubeflow-tpu.org/env.<KEY>`` annotations surfaced through a
    downward-API volume. The file format is one ``key="escaped value"``
    per line. Direct env always wins; annotations only fill gaps."""
    env = env if env is not None else os.environ
    if not os.path.exists(path):
        return dict(env)
    out = dict(env)
    prefix = "kubeflow-tpu.org/env."
    with open(path) as f:
        for line in f:
            key, eq, raw = line.strip().partition("=")
            if not eq or not key.startswith(prefix):
                continue
            val = raw.strip()
            if val.startswith('"') and val.endswith('"'):
                # downward-API files escape values Go-string style
                val = val[1:-1].replace('\\"', '"').replace("\\\\", "\\")
            out.setdefault(key[len(prefix):], val)
    return out


@dataclasses.dataclass
class StageInfo:
    """MPMD pipeline-stage rendezvous (parallel/mpmd.py): which stage
    this worker belongs to and where its neighbors' transports live.
    Stamped by the reconciler next to the jax.distributed world env when
    a JAXJob's worker template carries KFT_NUM_STAGES — stage workers do
    NOT join one jax.distributed world (that is the SPMD contract); each
    stage is its own program, and these addresses are the activation /
    grad-activation point-to-point links between them."""

    stage_id: int
    n_stages: int
    bind: str                      # this stage's listen address
    prev: Optional[str] = None     # stage_id-1's address (grads go here)
    next: Optional[str] = None     # stage_id+1's address (acts go here)
    stage_workers: int = 1         # workers per stage (multi-host stages)
    stage_proc_id: int = 0         # rank within the stage's worker group
    # interleaved-1F1B (virtual stages): each worker owns V model chunks
    # (chunk stage_id, stage_id+S, ...). The chunk graph wraps around the
    # worker ring, so the last worker also sends activations to worker 0
    # (wrap_next) and worker 0 sends grads to the last worker (wrap_prev).
    virtual_stages: int = 1
    wrap_next: Optional[str] = None  # stage S-1 -> stage 0 activation link
    wrap_prev: Optional[str] = None  # stage 0 -> stage S-1 grad link
    # per-stage worker group identity (multi-worker stages): the group is
    # the future per-stage jax.distributed world; size/rank/coord are its
    # rendezvous triplet, stamped even before that world exists so the
    # contract round-trips today.
    group_size: int = 1
    group_rank: int = 0
    group_coord: Optional[str] = None
    # elastic pipeline (ISSUE 20): the rendezvous epoch this worker was
    # launched into (the reconciler bumps job.status.rendezvous_epoch on
    # every replacement/gang restart and stamps it on NEW pods; a
    # replacement stage worker announces it through the snapshot dir so
    # surviving stages reform in process), and the per-pod incarnation
    # counter distinguishing a replacement from the pod it replaced.
    epoch: int = 0
    incarnation: int = 0

    @property
    def is_first(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last(self) -> bool:
        return self.stage_id == self.n_stages - 1


def stage_from_env(env: Optional[dict] = None) -> Optional[StageInfo]:
    """Parse the stage rendezvous env (downward-API annotations folded in
    like world_from_env). None when the job is not an MPMD pipeline."""
    env = env if env is not None else os.environ
    env = load_downward_env(env=env)
    if "KFT_NUM_STAGES" not in env:
        return None
    n = int(env["KFT_NUM_STAGES"])
    sid = int(env.get("KFT_STAGE_ID", "0"))
    workers = int(env.get("KFT_STAGE_WORKERS", "1"))
    return StageInfo(
        stage_id=sid,
        n_stages=n,
        bind=env.get("KFT_STAGE_BIND", "127.0.0.1:0"),
        prev=env.get("KFT_STAGE_PREV") or None,
        next=env.get("KFT_STAGE_NEXT") or None,
        stage_workers=workers,
        stage_proc_id=int(env.get("KFT_STAGE_PROC_ID", "0")),
        virtual_stages=int(env.get("KFT_VIRTUAL_STAGES", "1")),
        wrap_next=env.get("KFT_STAGE_WRAP_NEXT") or None,
        wrap_prev=env.get("KFT_STAGE_WRAP_PREV") or None,
        group_size=int(env.get("KFT_STAGE_GROUP_SIZE", str(workers))),
        group_rank=int(env.get("KFT_STAGE_GROUP_RANK",
                               env.get("KFT_STAGE_PROC_ID", "0"))),
        group_coord=env.get("KFT_STAGE_GROUP_COORD") or None,
        epoch=int(env.get("KFT_RENDEZVOUS_EPOCH", "0") or 0),
        incarnation=int(env.get("KFT_WORKER_INCARNATION", "0") or 0),
    )


def world_from_env(env: Optional[dict] = None) -> WorldInfo:
    env = env if env is not None else os.environ
    env = load_downward_env(env=env)
    return WorldInfo(
        coordinator=env.get("KFT_COORDINATOR", "127.0.0.1:8476"),
        num_processes=int(env.get("KFT_NUM_PROCESSES", "1")),
        process_id=int(env.get("KFT_PROCESS_ID", "0")),
        job_name=env.get("KFT_JOB_NAME", ""),
    )


def initialize(env: Optional[dict] = None, timeout_s: float = 300.0):
    """jax.distributed.initialize() from operator env; returns (world, mesh).

    Single-process jobs skip distributed init entirely (one less failure
    mode, and the common local/dev case).
    """
    import jax

    # persistent XLA compile cache (same contract as serving's
    # KFT_COMPILE_CACHE): a restarted or resubmitted job's first-step
    # compile becomes a cache read — the dominant submit→first-step phase
    # on anything but a brand-new program (BASELINE.md row 2)
    cache = (env or os.environ).get("KFT_COMPILE_CACHE")
    if cache:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    world = world_from_env(env)
    if world.num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=world.coordinator,
            num_processes=world.num_processes,
            process_id=world.process_id,
            initialization_timeout=int(timeout_s),
        )
    from kubeflow_tpu.parallel.mesh import mesh_from_topology_env

    mesh = mesh_from_topology_env(load_downward_env(env=env))
    return world, mesh


def wait_for_workers(world: WorldInfo, deadline_s: float = 300.0) -> None:
    """Barrier on world size: jax.device_count() must reach the global count."""
    import jax

    t0 = time.time()
    expected = world.num_processes * jax.local_device_count()
    while jax.device_count() < expected:
        if time.time() - t0 > deadline_s:
            raise TimeoutError(
                f"only {jax.device_count()}/{expected} devices after {deadline_s}s"
            )
        time.sleep(1.0)
