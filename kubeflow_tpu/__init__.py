"""kubeflow_tpu — a TPU-native ML orchestration platform + first-party JAX data plane.

Capability-equivalent rebuild of the Kubeflow distribution (reference:
``fast-ml/kubeflow``; see SURVEY.md — the reference mount was empty at survey
time, so parity targets come from SURVEY.md §2 and BASELINE.json) designed
TPU-first:

- ``parallel``   — device meshes, logical-axis sharding rules, ring attention,
                   collectives (DP/FSDP/TP/SP/CP/EP over ICI+DCN).
- ``ops``        — attention (XLA + Pallas flash), RoPE, norms, losses.
- ``models``     — Llama-3 family (flagship), ResNet, MNIST CNN.
- ``training``   — pjit train loop, mixed precision, remat, Orbax checkpointing.
- ``api``        — JAXJob/TFJob CRD-equivalent typed specs (RunPolicy,
                   ReplicaSpec, conditions) a la training-operator.
- ``controller`` — reconciling job controller + gang scheduling + local
                   multi-process backend (jax.distributed rendezvous).
- ``client``     — TrainingClient-style SDK.
- ``tune``       — Katib-equivalent HPO: experiments, suggestion algorithms,
                   trial controller, early stopping.
- ``pipelines``  — KFP-equivalent: Python DSL -> IR -> DAG executor + caching.
- ``metadata``   — MLMD-equivalent lineage store.
- ``serving``    — KServe-equivalent: InferenceService spec, model server
                   (V1/V2 inference protocol), JAX predictor with AOT compile
                   cache, dynamic batching.
"""

__version__ = "0.1.0"
