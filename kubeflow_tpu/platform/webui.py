"""Server-rendered web UI for the whole platform.

Capability parity with the reference's browser surfaces (SURVEY.md §2.3
katib-ui, §2.5 pipelines frontend, §2.6 centraldashboard + CRUD web apps:
jupyter-web-app / tensorboards-web-app), redesigned for the single-binary
operator: no JS framework, no separate UI deployments — every page is
HTML (+ inline SVG for plots and DAGs) rendered from the same in-process
controller state the daemon reconciles, and CRUD actions are plain HTML
forms POSTed back to the operator.

Security: every tenant-chosen string that lands in a page is escaped
(stored-XSS surface), and every mutating route re-checks per-namespace
authorization through the ``authz`` callback the operator supplies.
"""

from __future__ import annotations

import html as _html
import json
import os
from typing import Callable, Optional
from urllib.parse import parse_qs

_E = _html.escape

_CSS = """
body{font-family:system-ui,sans-serif;margin:0;background:#fafafa;color:#222}
nav{background:#1a2733;padding:.6rem 1rem}
nav a{color:#cfe3f5;text-decoration:none;margin-right:1.2rem;font-weight:500}
nav a:hover{color:#fff}
main{padding:1rem 1.5rem;max-width:70rem}
table{border-collapse:collapse;margin:.5rem 0 1.2rem;width:100%}
th,td{border:1px solid #ddd;padding:.35rem .6rem;text-align:left;
font-size:.9rem}
th{background:#eef2f5}
h1{font-size:1.3rem}h2{font-size:1.05rem;margin-top:1.4rem}
.ok{color:#1a7f37}.bad{color:#b42318}.warn{color:#9a6700}
form.inline{display:inline}
input,select{margin:.15rem .3rem .15rem 0;padding:.2rem .35rem}
button{padding:.25rem .7rem;cursor:pointer}
svg{background:#fff;border:1px solid #ddd}
code,pre{background:#f1f3f5;padding:.1rem .3rem;border-radius:3px}
pre{padding:.6rem;overflow-x:auto}
.pill{display:inline-block;padding:.05rem .5rem;border-radius:999px;
background:#e7ecf0;font-size:.85rem}
"""

_NAV = (
    ("/ui", "Overview"), ("/ui/jobs", "Jobs"),
    ("/ui/experiments", "Experiments"), ("/ui/serving", "Serving"),
    ("/ui/pipelines", "Pipelines"), ("/ui/notebooks", "Notebooks"),
    ("/ui/volumes", "Volumes"),
)


def _state_cls(state: str) -> str:
    if state in ("Succeeded", "Running", "Cached", "True"):
        return "ok"
    if state in ("Failed", "Killed"):
        return "bad"
    return "warn"


def _pill(state) -> str:
    s = str(getattr(state, "value", state))
    return f'<span class="pill {_state_cls(s)}">{_E(s)}</span>'


class Response:
    def __init__(self, code: int, body: str, ctype: str = "text/html",
                 location: Optional[str] = None):
        self.code = code
        self.body = body
        self.ctype = ctype
        self.location = location


def _redirect(to: str) -> Response:
    return Response(303, "", location=to)


def _not_found(what: str = "page") -> Response:
    return Response(404, f"<h1>404</h1><p>{_E(what)} not found</p>")


class WebUI:
    """Renders the platform's browser surfaces from live controller state.

    ``authz(namespace, verb) -> (allowed, reason)`` gates every mutation;
    ``visible(namespace) -> bool`` scopes listings per user (both default
    to open when the operator runs without auth). ``lock`` (the operator's
    RLock) serializes mutations with the reconcile loops."""

    def __init__(self, *, jobs=None, experiments=None, serving=None,
                 pipelines=None, notebooks=None, tensorboards=None,
                 metrics=None, lock=None):
        self.jobs = jobs                    # JobController
        self.experiments = experiments      # ExperimentManager
        self.serving = serving              # ServingController
        self.pipelines = pipelines          # PipelineClient
        self.notebooks = notebooks          # NotebookController
        self.tensorboards = tensorboards    # TensorBoardController
        self.metrics = metrics              # operator Metrics (optional)
        self._lock = lock

    # ---------------- routing ----------------

    def handle(self, method: str, path: str, body: str = "",
               visible: Optional[Callable[[str], bool]] = None,
               authz: Optional[Callable[[str, str], tuple[bool, str]]] = None,
               ) -> Optional[Response]:
        """Route one request. Returns None for non-/ui paths."""
        if path != "/ui" and not path.startswith("/ui/"):
            return None
        vis = visible or (lambda ns: True)
        can = authz or (lambda ns, verb: (True, ""))
        parts = [p for p in path.split("/") if p][1:]   # drop leading 'ui'
        try:
            if method == "GET":
                return self._route_get(parts, vis)
            if method == "POST":
                return self._route_post(parts, parse_qs(body), can)
        except Exception as e:   # render, never 500 with a stack trace
            return Response(400, f"<h1>error</h1><pre>{_E(str(e))}</pre>")
        return _not_found()

    def _route_get(self, parts: list[str], vis) -> Response:
        if not parts:
            return self._page("Overview", self.overview(vis))
        head = parts[0]
        # detail routes enforce the SAME namespace scoping as listings: a
        # direct URL into a foreign namespace must leak nothing (specs
        # carry env vars), so invisible renders exactly like nonexistent
        if head == "jobs":
            if len(parts) == 3:
                if not vis(parts[1]):
                    return self._page(f"Job {parts[2]}", "<p>not found</p>")
                return self._page(
                    f"Job {parts[2]}", self.job_detail(parts[1], parts[2]))
            return self._page("Jobs", self.jobs_list(vis))
        if head == "experiments":
            if len(parts) == 3:
                if not vis(parts[1]):
                    return self._page(
                        f"Experiment {parts[2]}", "<p>not found</p>")
                return self._page(
                    f"Experiment {parts[2]}",
                    self.experiment_detail(parts[1], parts[2]))
            return self._page("Experiments", self.experiments_list(vis))
        if head == "serving":
            return self._page("Serving", self.serving_list(vis))
        if head == "pipelines":
            if len(parts) == 3 and parts[1] == "runs":
                return self._page(
                    f"Run {parts[2]}", self.run_detail(parts[2]))
            return self._page("Pipelines", self.pipelines_list())
        if head == "notebooks":
            return self._page("Notebooks", self.notebooks_list(vis))
        if head == "volumes":
            if len(parts) >= 3 and parts[1] == "artifacts":
                return self._page(
                    f"Artifacts {parts[2]}",
                    self.artifacts_detail(parts[2], parts[3:]))
            return self._page("Volumes", self.volumes_list(vis))
        return _not_found()

    def _route_post(self, parts: list[str], form: dict, can) -> Response:
        def field(name: str, default: str = "") -> str:
            return (form.get(name) or [default])[0].strip()

        if len(parts) != 3 or parts[0] not in ("notebooks", "tensorboards"):
            return _not_found("action")
        kind, ns, action = parts
        allowed, reason = can(ns, "create" if action == "create" else "delete")
        if not allowed:
            return Response(403, f"<h1>403</h1><p>{_E(reason)}</p>")
        name = field("name")
        if not name or not name.replace("-", "").replace(".", "").isalnum():
            return Response(400, f"<h1>400</h1><p>invalid name {_E(name)!s}</p>")

        def mutate():
            if kind == "notebooks":
                from kubeflow_tpu.platform.notebooks import Notebook

                if self.notebooks is None:
                    raise LookupError("notebooks controller not wired")
                if action == "create":
                    nb = Notebook(name=name, namespace=ns)
                    if field("image"):
                        nb.image = field("image")
                    if field("cull_idle_seconds"):
                        nb.cull_idle_seconds = float(
                            field("cull_idle_seconds"))
                    self.notebooks.apply(nb)
                elif action == "delete":
                    self.notebooks.delete(ns, name)
                elif action == "touch":
                    self.notebooks.touch(ns, name)
                else:
                    raise LookupError(f"unknown action {action}")
            else:
                from kubeflow_tpu.platform.notebooks import TensorBoard

                if self.tensorboards is None:
                    raise LookupError("tensorboard controller not wired")
                if action == "create":
                    self.tensorboards.apply(TensorBoard(
                        name=name, namespace=ns, logdir=field("logdir")))
                elif action == "delete":
                    self.tensorboards.delete(ns, name)
                else:
                    raise LookupError(f"unknown action {action}")

        if self._lock is not None:
            with self._lock:
                mutate()
        else:
            mutate()
        return _redirect("/ui/notebooks")

    # ---------------- layout ----------------

    @staticmethod
    def _page(title: str, content: str) -> Response:
        nav = "".join(f'<a href="{href}">{label}</a>'
                      for href, label in _NAV)
        return Response(200, (
            "<!doctype html><html><head>"
            f"<title>{_E(title)} — kubeflow-tpu</title>"
            f"<style>{_CSS}</style></head><body>"
            f"<nav>{nav}</nav><main><h1>{_E(title)}</h1>{content}"
            "</main></body></html>"))

    # ---------------- overview ----------------

    def overview(self, vis) -> str:
        cards = []

        def card(label: str, n: int, href: str):
            cards.append(
                f'<tr><td><a href="{href}">{_E(label)}</a></td>'
                f"<td>{n}</td></tr>")

        if self.jobs is not None:
            card("Training jobs",
                 sum(1 for (ns, _) in self.jobs.jobs if vis(ns)), "/ui/jobs")
        if self.experiments is not None:
            card("Experiments",
                 sum(1 for e in self.experiments.list() if vis(e.namespace)),
                 "/ui/experiments")
        if self.serving is not None:
            card("InferenceServices",
                 sum(1 for (ns, _) in self.serving.services if vis(ns)),
                 "/ui/serving")
        if self.pipelines is not None:
            card("Pipeline runs", len(self.pipelines.list_runs()),
                 "/ui/pipelines")
        if self.notebooks is not None:
            card("Notebooks",
                 sum(1 for (ns, _) in self.notebooks.notebooks if vis(ns)),
                 "/ui/notebooks")
        out = ("<table><tr><th>Resource</th><th>Count</th></tr>"
               + "".join(cards) + "</table>")
        if self.metrics is not None:
            interesting = (
                "kft_jobs_registered", "kft_gang_queue_depth",
                "kft_jobs_submitted_total", "kft_reconcile_total")
            rows = "".join(
                f"<tr><td><code>{_E(k)}</code></td><td>{v:g}</td></tr>"
                for k in interesting
                for v in [self.metrics.get(k)] if v is not None)
            if rows:
                out += ("<h2>Controller metrics</h2><table>"
                        "<tr><th>Metric</th><th>Value</th></tr>"
                        f"{rows}</table>")
        return out

    # ---------------- jobs ----------------

    def jobs_list(self, vis) -> str:
        if self.jobs is None:
            return "<p>job controller not wired</p>"
        rows = []
        for (ns, name), job in sorted(self.jobs.jobs.items()):
            if not vis(ns):
                continue
            cond = job.status.condition()
            rows.append(
                f"<tr><td>{_E(ns)}</td>"
                f'<td><a href="/ui/jobs/{_E(ns)}/{_E(name)}">{_E(name)}</a>'
                f"</td><td>{_E(job.kind)}</td>"
                f"<td>{_pill(cond.value if cond else 'Pending')}</td>"
                f"<td>{job.status.restart_count}</td></tr>")
        return ("<table><tr><th>Namespace</th><th>Name</th><th>Kind</th>"
                "<th>State</th><th>Restarts</th></tr>"
                + "".join(rows) + "</table>")

    def job_detail(self, ns: str, name: str) -> str:
        job = self.jobs.get(ns, name) if self.jobs is not None else None
        if job is None:
            return "<p>not found</p>"
        conds = "".join(
            f"<tr><td>{_pill(c.type.value)}</td><td>{_E(c.reason)}</td>"
            f"<td>{_E(c.message)}</td></tr>"
            for c in job.status.conditions)
        reps = "".join(
            f"<tr><td>{_E(rt)}</td><td>{rs.active}</td><td>{rs.succeeded}"
            f"</td><td>{rs.failed}</td></tr>"
            for rt, rs in job.status.replica_statuses.items())
        from kubeflow_tpu.api.types import to_yaml

        return (
            f"<p>kind <code>{_E(job.kind)}</code> · uid "
            f"<code>{_E(job.uid)}</code> · restarts "
            f"{job.status.restart_count}</p>"
            "<h2>Conditions</h2><table><tr><th>Type</th><th>Reason</th>"
            f"<th>Message</th></tr>{conds}</table>"
            "<h2>Replicas</h2><table><tr><th>Type</th><th>Active</th>"
            f"<th>Succeeded</th><th>Failed</th></tr>{reps}</table>"
            f"<h2>Spec</h2><pre>{_E(to_yaml(job))}</pre>")

    # ---------------- experiments (katib-ui role) ----------------

    def experiments_list(self, vis) -> str:
        if self.experiments is None:
            return "<p>experiment manager not wired</p>"
        rows = []
        for e in self.experiments.list():
            if not vis(e.namespace):
                continue
            state = ("Succeeded" if e.succeeded
                     else "Failed" if e.failed else "Running")
            best = e.best_trial
            rows.append(
                f"<tr><td>{_E(e.namespace)}</td>"
                f'<td><a href="/ui/experiments/{_E(e.namespace)}/{_E(e.name)}">'
                f"{_E(e.name)}</a></td><td>{_pill(state)}</td>"
                f"<td>{len(e.trials)}/{e.max_trial_count}</td>"
                f"<td>{'' if best is None else f'{best.objective_value:.6g}'}"
                "</td></tr>")
        return ("<table><tr><th>Namespace</th><th>Name</th><th>State</th>"
                "<th>Trials</th><th>Best objective</th></tr>"
                + "".join(rows) + "</table>")

    def experiment_detail(self, ns: str, name: str) -> str:
        exp = (self.experiments.get(ns, name)
               if self.experiments is not None else None)
        if exp is None:
            return "<p>not found</p>"
        best = exp.best_trial
        rows = []
        for t in exp.trials:
            is_best = best is not None and t.name == best.name
            rows.append(
                f"<tr><td>{_E(t.name)}{' ★' if is_best else ''}</td>"
                f"<td>{_pill(t.state.value)}</td>"
                f"<td><code>{_E(json.dumps(t.parameters))}</code></td>"
                f"<td>{'' if t.objective_value is None else f'{t.objective_value:.6g}'}"
                "</td></tr>")
        obj = exp.objective
        return (
            f"<p>algorithm <code>{_E(exp.algorithm.name)}</code> · objective "
            f"<code>{_E(obj.goal_type.value)} {_E(obj.metric_name)}</code>"
            + (f" · goal {obj.goal:g}" if obj.goal is not None else "")
            + (f" · done ({_E(exp.completion_reason)})"
               if exp.succeeded or exp.failed else "")
            + "</p>"
            + self._objective_svg(exp)
            + "<h2>Trials</h2><table><tr><th>Trial</th><th>State</th>"
            f"<th>Parameters</th><th>Objective</th></tr>{''.join(rows)}"
            "</table>")

    @staticmethod
    def _objective_svg(exp) -> str:
        """Objective-vs-trial scatter with a running-best line — the
        katib-ui experiment plot, as dependency-free inline SVG."""
        pts = [(i, t.objective_value) for i, t in enumerate(exp.trials)
               if t.objective_value is not None]
        if len(pts) < 1:
            return ""
        w, h, pad = 640, 220, 36
        ys = [y for _, y in pts]
        lo, hi = min(ys), max(ys)
        if hi - lo < 1e-12:
            lo, hi = lo - 0.5, hi + 0.5
        n = max(1, len(exp.trials) - 1)

        def sx(i):
            return pad + (w - 2 * pad) * (i / n)

        def sy(v):
            return h - pad - (h - 2 * pad) * ((v - lo) / (hi - lo))

        circles = "".join(
            f'<circle cx="{sx(i):.1f}" cy="{sy(y):.1f}" r="3.5" '
            'fill="#2563eb" fill-opacity="0.8"/>' for i, y in pts)
        # running best (respecting the objective direction)
        best_path, cur = [], None
        for i, y in pts:
            if cur is None or exp.objective.better(y, cur):
                cur = y
            best_path.append(f"{sx(i):.1f},{sy(cur):.1f}")
        line = (f'<polyline points="{" ".join(best_path)}" fill="none" '
                'stroke="#16a34a" stroke-width="1.5"/>') if best_path else ""
        axis = (
            f'<line x1="{pad}" y1="{h-pad}" x2="{w-pad}" y2="{h-pad}" '
            'stroke="#888"/>'
            f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{h-pad}" '
            'stroke="#888"/>'
            f'<text x="{pad}" y="{pad-8}" font-size="11" fill="#555">'
            f"{hi:.4g}</text>"
            f'<text x="{pad}" y="{h-pad+14}" font-size="11" fill="#555">'
            f"{lo:.4g}</text>"
            f'<text x="{w-pad-40}" y="{h-pad+14}" font-size="11" '
            f'fill="#555">trial {len(exp.trials)-1}</text>')
        return (f'<svg width="{w}" height="{h}" viewBox="0 0 {w} {h}" '
                'role="img" aria-label="objective per trial">'
                f"{axis}{line}{circles}</svg>")

    # ---------------- serving ----------------

    def serving_list(self, vis) -> str:
        if self.serving is None:
            return "<p>serving controller not wired</p>"
        rows = []
        for (ns, name), isvc in sorted(self.serving.services.items()):
            if not vis(ns):
                continue
            traffic = ", ".join(
                f"{_E(str(rev))}: {pct}%"
                for rev, pct in isvc.status.traffic.items())
            rows.append(
                f"<tr><td>{_E(ns)}</td><td>{_E(name)}</td>"
                f"<td>{_pill('True' if isvc.status.ready else 'False')}</td>"
                f"<td>{_E(isvc.status.latest_revision or '')}</td>"
                f"<td>{traffic}</td>"
                f"<td><code>{_E(isvc.status.url or '')}</code></td></tr>")
        return ("<table><tr><th>Namespace</th><th>Name</th><th>Ready</th>"
                "<th>Latest revision</th><th>Traffic</th><th>URL</th></tr>"
                + "".join(rows) + "</table>")

    # ---------------- pipelines (frontend role) ----------------

    def pipelines_list(self) -> str:
        if self.pipelines is None:
            return "<p>pipeline client not wired</p>"
        pipes = "".join(f"<li><code>{_E(p)}</code></li>"
                        for p in self.pipelines.list_pipelines())
        runs = "".join(
            f'<tr><td><a href="/ui/pipelines/runs/{_E(r.run_id)}">'
            f"{_E(r.run_id)}</a></td><td>{_pill(r.state)}</td>"
            f"<td>{len(r.tasks)}</td></tr>"
            for r in self.pipelines.list_runs())
        rec = "".join(
            f"<tr><td>{_E(rr.name)}</td><td><code>{_E(rr.pipeline)}</code>"
            f"</td><td>{rr.interval_seconds:g}s</td>"
            f"<td>{'yes' if rr.enabled else 'no'}</td>"
            f"<td>{len(rr.run_ids)}</td></tr>"
            for rr in self.pipelines.list_recurring())
        return (
            f"<h2>Pipelines</h2><ul>{pipes or '<li>none uploaded</li>'}</ul>"
            "<h2>Runs</h2><table><tr><th>Run</th><th>State</th>"
            f"<th>Tasks</th></tr>{runs}</table>"
            "<h2>Recurring runs</h2><table><tr><th>Name</th><th>Pipeline</th>"
            f"<th>Interval</th><th>Enabled</th><th>Fired</th></tr>{rec}"
            "</table>")

    def run_detail(self, run_id: str) -> str:
        run = (self.pipelines.get_run(run_id)
               if self.pipelines is not None else None)
        if run is None:
            return "<p>not found</p>"
        rows = "".join(
            f"<tr><td>{_E(t.name)}</td><td>{_pill(t.state)}</td>"
            f"<td>{t.attempts}</td>"
            f"<td><code>{_E(json.dumps(t.outputs, default=str)[:200])}</code>"
            f"</td><td>{_E(t.error[:200])}</td></tr>"
            for t in run.tasks.values())
        err = getattr(run, "error", "")
        return (
            f"<p>state {_pill(run.state)} · params "
            f"<code>{_E(json.dumps(run.params, default=str))}</code></p>"
            + (f'<p class="bad">launch error: <code>{_E(err)}</code></p>'
               if err else "")
            + self._dag_svg(run)
            + "<h2>Tasks</h2><table><tr><th>Task</th><th>State</th>"
            f"<th>Attempts</th><th>Outputs</th><th>Error</th></tr>{rows}"
            "</table>")

    def _dag_svg(self, run) -> str:
        """Run DAG as inline SVG: nodes colored by state, edges from the
        uploaded pipeline's task graph (explicit .after deps + data deps)."""
        edges = self._run_edges(run)
        names = list(run.tasks)
        if not names:
            return ""
        # topological layering by longest path from a root
        depth = {n: 0 for n in names}
        for _ in range(len(names)):
            changed = False
            for src, dst in edges:
                if src in depth and dst in depth \
                        and depth[dst] < depth[src] + 1:
                    depth[dst] = depth[src] + 1
                    changed = True
            if not changed:
                break
        layers: dict[int, list[str]] = {}
        for n in names:
            layers.setdefault(depth[n], []).append(n)
        box_w, box_h, gap_x, gap_y, pad = 150, 34, 40, 28, 20
        n_layers = max(layers) + 1
        max_rows = max(len(v) for v in layers.values())
        w = pad * 2 + n_layers * box_w + (n_layers - 1) * gap_x
        h = pad * 2 + max_rows * box_h + (max_rows - 1) * gap_y
        pos = {}
        for d, members in layers.items():
            for r, n in enumerate(sorted(members)):
                x = pad + d * (box_w + gap_x)
                y = pad + r * (box_h + gap_y)
                pos[n] = (x, y)
        fill = {"Succeeded": "#dcfce7", "Cached": "#dbeafe",
                "Failed": "#fee2e2", "Running": "#fef9c3",
                "Skipped": "#e5e7eb", "Pending": "#f3f4f6"}
        parts = ['<defs><marker id="arr" viewBox="0 0 10 10" refX="9" '
                 'refY="5" markerWidth="7" markerHeight="7" orient="auto">'
                 '<path d="M0,0L10,5L0,10z" fill="#94a3b8"/></marker></defs>']
        for src, dst in edges:
            if src not in pos or dst not in pos:
                continue
            x1, y1 = pos[src][0] + box_w, pos[src][1] + box_h / 2
            x2, y2 = pos[dst][0], pos[dst][1] + box_h / 2
            parts.append(
                f'<line x1="{x1}" y1="{y1}" x2="{x2}" y2="{y2}" '
                'stroke="#94a3b8" stroke-width="1.2" marker-end="url(#arr)"/>')
        for n, (x, y) in pos.items():
            state = str(run.tasks[n].state.value)
            parts.append(
                f'<rect x="{x}" y="{y}" width="{box_w}" height="{box_h}" '
                f'rx="6" fill="{fill.get(state, "#f3f4f6")}" '
                'stroke="#64748b"/>'
                f'<text x="{x + box_w / 2}" y="{y + box_h / 2 + 4}" '
                'text-anchor="middle" font-size="11">'
                f"{_E(n[:22])}</text>")
        return (f'<svg width="{w}" height="{h}" viewBox="0 0 {w} {h}" '
                f'role="img" aria-label="run DAG">{"".join(parts)}</svg>')

    def _run_edges(self, run) -> list[tuple[str, str]]:
        """Edges between the run's expanded task instances, derived from
        the pipeline graph (instance names are '<task>' or '<task>-<i>...'
        for loop iterations)."""
        from kubeflow_tpu.pipelines import dsl

        # the run's context records its pipeline name authoritatively
        # (runner.run put_context properties); fall back to the longest
        # name prefix for stores that predate that record
        pipe = None
        meta = getattr(self.pipelines.runner, "metadata", None)
        if meta is not None:
            ctx_rec = meta.context_by_name("pipeline_run", run.run_id)
            if ctx_rec is not None:
                pipe = self.pipelines._pipelines.get(
                    ctx_rec.properties.get("pipeline"))
        if pipe is None:
            for pname in sorted(self.pipelines.list_pipelines(),
                                key=len, reverse=True):
                if run.run_id == pname or \
                        run.run_id.startswith(pname + "-"):
                    pipe = self.pipelines._pipelines[pname]
                    break
        if pipe is None:
            return []
        try:
            ctx = pipe.trace(dict(run.params))
        except Exception:
            return []
        base_edges = set()
        for t in ctx.tasks.values():
            for dep in t.dependencies:
                base_edges.add((dep, t.name))
            for v in t.arguments.values():
                for ref in _refs(v, dsl.OutputRef):
                    base_edges.add((ref.task, t.name))
            for cond in t.conditions:
                for ref in _refs((cond.lhs, cond.rhs), dsl.OutputRef):
                    base_edges.add((ref.task, t.name))

        def instances(base: str) -> list[str]:
            return [n for n in run.tasks
                    if n == base or n.startswith(base + "-")]

        out = []
        for src, dst in sorted(base_edges):
            for s in instances(src):
                for d in instances(dst):
                    out.append((s, d))
        return out

    # ---------------- notebooks + tensorboards (CRUD web apps) ----------

    def notebooks_list(self, vis) -> str:
        out = []
        if self.notebooks is not None:
            rows = "".join(
                f"<tr><td>{_E(ns)}</td><td>{_E(name)}</td>"
                f"<td>{_E(nb.image)}</td>"
                f"<td>{_pill('Stopped' if nb.stopped else 'Running')}</td>"
                "<td>"
                f'<form class="inline" method="post" '
                f'action="/ui/notebooks/{_E(ns)}/touch">'
                f'<input type="hidden" name="name" value="{_E(name)}">'
                "<button>connect</button></form> "
                f'<form class="inline" method="post" '
                f'action="/ui/notebooks/{_E(ns)}/delete">'
                f'<input type="hidden" name="name" value="{_E(name)}">'
                "<button>delete</button></form></td></tr>"
                for (ns, name), nb in sorted(self.notebooks.notebooks.items())
                if vis(ns))
            out.append(
                "<h2>Notebooks</h2><table><tr><th>Namespace</th>"
                "<th>Name</th><th>Image</th><th>State</th><th></th></tr>"
                f"{rows}</table>"
                '<form method="post" action="/ui/notebooks/default/create" '
                'onsubmit="this.action=\'/ui/notebooks/\'+'
                "this.ns.value+'/create'\">"
                '<input name="ns" value="default" size="10">'
                '<input name="name" placeholder="name" required>'
                '<input name="image" placeholder="image (optional)">'
                '<input name="cull_idle_seconds" placeholder="cull secs" '
                'size="8"><button>Create notebook</button></form>')
        if self.tensorboards is not None:
            rows = "".join(
                f"<tr><td>{_E(ns)}</td><td>{_E(name)}</td>"
                f"<td><code>{_E(tb.logdir)}</code></td>"
                "<td>"
                f'<form class="inline" method="post" '
                f'action="/ui/tensorboards/{_E(ns)}/delete">'
                f'<input type="hidden" name="name" value="{_E(name)}">'
                "<button>delete</button></form></td></tr>"
                for (ns, name), tb in sorted(self.tensorboards.boards.items())
                if vis(ns))
            out.append(
                "<h2>TensorBoards</h2><table><tr><th>Namespace</th>"
                "<th>Name</th><th>Logdir</th><th></th></tr>"
                f"{rows}</table>"
                '<form method="post" '
                'action="/ui/tensorboards/default/create" '
                'onsubmit="this.action=\'/ui/tensorboards/\'+'
                "this.ns.value+'/create'\">"
                '<input name="ns" value="default" size="10">'
                '<input name="name" placeholder="name" required>'
                '<input name="logdir" placeholder="logdir">'
                "<button>Create tensorboard</button></form>")
        return "".join(out) or "<p>no notebook controllers wired</p>"


    # ---------------- volumes + artifacts (the pvcviewer role) ----------

    def volumes_list(self, vis) -> str:
        """Storage browser: job-declared volume mounts (namespace-scoped)
        and pipeline-run artifact stores — the pvcviewer-equivalent."""
        out = []
        if self.jobs is not None:
            rows = []
            for (ns, name), job in sorted(self.jobs.jobs.items()):
                if not vis(ns):
                    continue
                for rtype, spec in job.replica_specs.items():
                    for vol, mount in sorted(
                            spec.template.volumes.items()):
                        rows.append(
                            f"<tr><td>{_E(ns)}</td>"
                            f'<td><a href="/ui/jobs/{_E(ns)}/{_E(name)}">'
                            f"{_E(name)}</a></td><td>{_E(rtype)}</td>"
                            f"<td>{_E(vol)}</td>"
                            f"<td><code>{_E(mount)}</code></td></tr>")
            out.append(
                "<h2>Job volume mounts</h2>"
                "<table><tr><th>Namespace</th><th>Job</th><th>Replica</th>"
                "<th>Volume</th><th>Mount</th></tr>"
                + "".join(rows) + "</table>"
                if rows else "<h2>Job volume mounts</h2><p>none declared</p>")
        if self.pipelines is not None:
            rows = "".join(
                f'<tr><td><a href="/ui/volumes/artifacts/{_E(r.run_id)}">'
                f"{_E(r.run_id)}</a></td>"
                f"<td>{_pill(r.state.value if hasattr(r.state, 'value') else str(r.state))}</td></tr>"
                for r in self.pipelines.list_runs())
            out.append(
                "<h2>Pipeline artifact stores</h2>"
                "<table><tr><th>Run</th><th>State</th></tr>"
                f"{rows}</table>")
        return "".join(out) or "<p>no storage-backed controllers wired</p>"

    def artifacts_detail(self, run_id: str, rest: list[str]) -> str:
        """Browse one run's artifact directory; small text artifacts
        render inline. Paths resolve strictly inside the run dir."""
        if self.pipelines is None:
            return "<p>no pipeline runner wired</p>"
        workdir = getattr(self.pipelines.runner, "workdir", None)
        if workdir is None:
            return "<p>runner has no artifact directory</p>"
        run_dir = os.path.realpath(os.path.join(workdir, run_id))
        if (not run_dir.startswith(os.path.realpath(workdir) + os.sep)
                or not os.path.isdir(run_dir)):
            return "<p>not found</p>"
        target = os.path.realpath(os.path.join(run_dir, *rest))
        if not (target == run_dir
                or target.startswith(run_dir + os.sep)) \
                or not os.path.exists(target):
            return "<p>not found</p>"
        if os.path.isfile(target):
            size = os.path.getsize(target)
            if size > 65536:
                return (f"<p>{_E(os.path.basename(target))}: {size} bytes "
                        "(too large to preview)</p>")
            with open(target, "rb") as f:
                data = f.read()
            try:
                text = data.decode()
            except UnicodeDecodeError:
                return (f"<p>{_E(os.path.basename(target))}: {size} bytes "
                        "(binary)</p>")
            return f"<pre>{_E(text)}</pre>"
        rows = []
        for entry in sorted(os.listdir(target)):
            full = os.path.join(target, entry)
            href = "/".join(["/ui/volumes/artifacts", run_id]
                            + rest + [entry])
            kind = "dir" if os.path.isdir(full) else "file"
            size = "" if kind == "dir" else str(os.path.getsize(full))
            rows.append(
                f'<tr><td><a href="{_E(href)}">{_E(entry)}</a></td>'
                f"<td>{kind}</td><td>{size}</td></tr>")
        return ("<table><tr><th>Name</th><th>Type</th><th>Bytes</th></tr>"
                + "".join(rows) + "</table>") if rows else "<p>empty</p>"


def _refs(v, ref_type):
    """Yield every OutputRef nested in a task-argument value."""
    if isinstance(v, ref_type):
        yield v
    elif isinstance(v, dict):
        for x in v.values():
            yield from _refs(x, ref_type)
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _refs(x, ref_type)
