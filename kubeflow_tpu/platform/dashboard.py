"""Central dashboard — thin status aggregation over all controllers
(SURVEY.md §2.6 centraldashboard, reduced to its capability: one place that
lists everything a user owns, JSON + minimal HTML, namespace-scoped by the
profile access rules)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class Dashboard:
    """Aggregates controllers; access-checked by a ProfileController."""

    def __init__(self, *, jobs=None, experiments=None, serving=None,
                 pipelines=None, notebooks=None, profiles=None):
        self.jobs = jobs
        self.experiments = experiments
        self.serving = serving
        self.pipelines = pipelines
        self.notebooks = notebooks
        self.profiles = profiles

    def snapshot(self, user: Optional[str] = None) -> dict:
        """Everything visible to `user` (all namespaces when user is None
        or no profile controller is wired)."""
        allowed = None
        if user is not None and self.profiles is not None:
            allowed = set(self.profiles.namespaces_for(user))

        def visible(ns: str) -> bool:
            return allowed is None or ns in allowed

        out: dict = {"namespaces": sorted(allowed) if allowed else "all"}
        if self.jobs is not None:
            out["jobs"] = [
                {"namespace": ns, "name": name,
                 "kind": job.kind,
                 "state": (job.status.condition().value
                           if job.status.condition() else "Pending"),
                 "restarts": job.status.restart_count}
                for (ns, name), job in sorted(self.jobs.jobs.items())
                if visible(ns)
            ]
        if self.experiments is not None:
            experiments = (self.experiments() if callable(self.experiments)
                           else self.experiments)
            out["experiments"] = [
                {"name": e.name,
                 "trials": len(e.trials),
                 "best": (e.best_trial.objective_value
                          if e.best_trial else None),
                 "done": e.succeeded or e.failed}
                for e in experiments if visible(e.namespace)
            ]
        if self.serving is not None:
            out["inference_services"] = [
                {"namespace": ns, "name": name,
                 "ready": isvc.status.ready,
                 "traffic": isvc.status.traffic}
                for (ns, name), isvc in sorted(self.serving.services.items())
                if visible(ns)
            ]
        if self.pipelines is not None:
            out["pipeline_runs"] = [
                {"run_id": r.run_id, "state": r.state.value}
                for r in self.pipelines.list_runs()
            ]
        if self.notebooks is not None:
            out["notebooks"] = [
                {"namespace": ns, "name": name, "stopped": nb.stopped}
                for (ns, name), nb in sorted(
                    self.notebooks.notebooks.items())
                if visible(ns)
            ]
        return out

    @staticmethod
    def render_html(snap: dict, webui_mounted: bool = False) -> str:
        """The ONE html renderer (operator route + standalone server).
        Tenant-chosen names land in this page, so everything is escaped —
        unescaped interpolation here is stored XSS against whoever views
        the dashboard."""
        import html as _html

        rows = "".join(
            f"<h2>{_html.escape(str(k))}</h2>"
            f"<pre>{_html.escape(json.dumps(v, indent=1))}</pre>"
            for k, v in snap.items())
        # /ui routes exist only when the operator mounts a WebUI; the
        # standalone dashboard server must not render dead links
        links = "".join(
            f'<a href="{href}" style="margin-right:1rem">{label}</a>'
            for href, label in (
                ("/ui", "Web UI"), ("/ui/jobs", "Jobs"),
                ("/ui/pipelines", "Pipelines"),
                ("/ui/volumes", "Volumes &amp; artifacts"))
        ) if webui_mounted else ""
        nav = f"<nav>{links}</nav>" if links else ""
        return ("<html><title>kubeflow-tpu</title><body>"
                f"<h1>kubeflow-tpu dashboard</h1>{nav}"
                f"{rows}</body></html>")

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                user = (parse_qs(parsed.query).get("user") or [None])[0]
                if parsed.path == "/api/snapshot":
                    body = json.dumps(outer.snapshot(user)).encode()
                    ctype = "application/json"
                elif parsed.path in ("/", "/index.html"):
                    body = outer.render_html(outer.snapshot(user)).encode()
                    ctype = "text/html"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer((host, port), Handler)
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server
