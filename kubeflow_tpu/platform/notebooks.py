"""Notebook + TensorBoard controllers (SURVEY.md §2.6: notebook-controller
with idle culling; tensorboard-controller as CRD -> viewer Deployment)."""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from kubeflow_tpu.controller.cluster import (
    Cluster, Pod, Service, create_and_admit,
)


@dataclasses.dataclass
class Notebook:
    name: str
    namespace: str = "default"
    image: str = "kubeflow-tpu/notebook:latest"
    cpu: str = "2"
    memory: str = "8Gi"
    tpu_chips: int = 0
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    volumes: dict[str, str] = dataclasses.field(default_factory=dict)
    # culling
    cull_idle_seconds: Optional[float] = 3600.0
    last_activity: float = dataclasses.field(default_factory=time.time)
    stopped: bool = False


@dataclasses.dataclass
class TensorBoard:
    name: str
    namespace: str = "default"
    logdir: str = ""
    image: str = "kubeflow-tpu/tensorboard:latest"


class NotebookController:
    """Reconciles Notebooks into a pod+service each; culls idle ones by
    stopping the pod (spec retained — restart on next activity)."""

    def __init__(self, cluster: Cluster, pod_mutator=None):
        self.cluster = cluster
        self.notebooks: dict[tuple[str, str], Notebook] = {}
        self.pod_mutator = pod_mutator

    def apply(self, nb: Notebook) -> Notebook:
        self.notebooks[(nb.namespace, nb.name)] = nb
        self.reconcile(nb.namespace, nb.name)
        return nb

    def delete(self, namespace: str, name: str) -> None:
        self.notebooks.pop((namespace, name), None)
        self.cluster.delete_pod(namespace, f"notebook-{name}")
        self.cluster.delete_service(namespace, f"notebook-{name}")

    def reconcile(self, namespace: str, name: str) -> Optional[Notebook]:
        nb = self.notebooks.get((namespace, name))
        if nb is None:
            return None
        pod_name = f"notebook-{name}"
        if nb.stopped:
            self.cluster.delete_pod(namespace, pod_name)
            return nb
        if self.cluster.get_pod(namespace, pod_name) is None:
            env = dict(nb.env)
            command: list = []
            from kubeflow_tpu.controller.cluster import allocate_bind

            if getattr(self.cluster, "allocate_port", None) is not None:
                # image-less backend (local processes): an empty command
                # would exit immediately — run the stub notebook server on
                # a per-pod port so the pod is genuinely Running and the
                # service resolves to a live endpoint. Real clusters keep
                # command=[] and run the notebook image's entrypoint.
                import sys

                if "KFT_BIND" not in env:
                    env["KFT_BIND"] = allocate_bind(self.cluster)
                env.setdefault("KFT_NOTEBOOK_NAME", name)
                command = [sys.executable, "-m",
                           "kubeflow_tpu.platform.notebook_stub"]
            pod = Pod(
                name=pod_name, namespace=namespace,
                labels={"notebook": name, "app": "notebook"},
                env=env, command=command,
            )
            if self.pod_mutator is not None:
                pod = self.pod_mutator(pod)
            create_and_admit(self.cluster, pod)   # no gang barrier
        if self.cluster.get_service(namespace, pod_name) is None:
            self.cluster.create_service(Service(
                name=pod_name, namespace=namespace,
                selector={"notebook": name}, port=8888))
        return nb

    def touch(self, namespace: str, name: str) -> None:
        """Record user activity (resets the culling clock; restarts a
        culled notebook)."""
        nb = self.notebooks[(namespace, name)]
        nb.last_activity = time.time()
        if nb.stopped:
            nb.stopped = False
        self.reconcile(namespace, name)

    def cull_idle(self, now: Optional[float] = None) -> list[str]:
        """Stop notebooks idle past their cull window. Returns culled names."""
        now = time.time() if now is None else now
        culled = []
        for nb in self.notebooks.values():
            if nb.stopped or nb.cull_idle_seconds is None:
                continue
            if now - nb.last_activity > nb.cull_idle_seconds:
                nb.stopped = True
                self.reconcile(nb.namespace, nb.name)
                culled.append(f"{nb.namespace}/{nb.name}")
        return culled


class TensorBoardController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self.boards: dict[tuple[str, str], TensorBoard] = {}

    def apply(self, tb: TensorBoard) -> TensorBoard:
        self.boards[(tb.namespace, tb.name)] = tb
        pod_name = f"tensorboard-{tb.name}"
        if self.cluster.get_pod(tb.namespace, pod_name) is None:
            pod = Pod(
                name=pod_name, namespace=tb.namespace,
                labels={"tensorboard": tb.name},
                env={"TB_LOGDIR": tb.logdir},
                command=["tensorboard", "--logdir", tb.logdir],
            )
            create_and_admit(self.cluster, pod)
        if self.cluster.get_service(tb.namespace, pod_name) is None:
            self.cluster.create_service(Service(
                name=pod_name, namespace=tb.namespace,
                selector={"tensorboard": tb.name}, port=6006))
        return tb

    def delete(self, namespace: str, name: str) -> None:
        self.boards.pop((namespace, name), None)
        self.cluster.delete_pod(namespace, f"tensorboard-{name}")
        self.cluster.delete_service(namespace, f"tensorboard-{name}")
