"""Install manifests — the `kubectl apply -k example/` equivalent
(SURVEY.md §2.6 'manifests distribution', §3.5 bring-up): render the whole
platform as Kubernetes YAML with ZERO GPU dependencies (BASELINE.md: no
NVIDIA device plugin / runtime class anywhere in the default install).

``render_platform()`` returns the multi-doc YAML; overlays mutate the base
(kustomize-style patches) without touching it.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

import yaml

API_GROUP = "kubeflow-tpu.org"
VERSION = "v1"

# Only kinds the installed daemon actually reconciles get CRDs: rendering
# a CRD nothing watches strands user objects forever (review finding).
# Pipelines/notebooks/tensorboards/profiles/poddefaults are SDK/library
# tier in the single-binary architecture — their state lives in the
# metadata store or the operator's auth file, not in CRs.
CRD_KINDS = [
    ("jaxjobs", "JAXJob"),
    ("tfjobs", "TFJob"),
    ("pytorchjobs", "PyTorchJob"),
    ("xgboostjobs", "XGBoostJob"),
    ("experiments", "Experiment"),
    ("trials", "Trial"),
    ("inferenceservices", "InferenceService"),
    ("servingruntimes", "ServingRuntime"),
]

# The single-binary architecture (SURVEY.md §7): ONE operator Deployment
# runs the training + HPO + serving control loops AND the dashboard
# (python -m kubeflow_tpu.controller serve — the REAL entrypoint in this
# repo, built into the platform image by the root Dockerfile), plus the
# native C++ metadata store (raw length-prefixed TCP — its probe is a TCP
# socket check, never HTTP). Commands/args/ports here are validated
# against the actual CLI parser and bind surface by tests — the install
# path cannot drift from the codebase. Pipelines run through the SDK
# (LocalRunner + durable run state in the metadata store), not a CRD
# controller, so no pipelines apiserver Deployment exists to render.
PLATFORM_IMAGE = "kubeflow-tpu/platform:latest"
OPERATOR_ARGS = ["serve", "--config", "/etc/kft/platform.json",
                 "--state-dir", "/data",
                 "--auth-tokens", "/etc/kft/auth.json",
                 "--bind-host", "0.0.0.0", "--port", "8080",
                 # worker pods beat liveness back over HTTP (no shared fs
                 # on a real cluster): the operator Service DNS name
                 "--advertise-url", "http://kft-operator.kubeflow-tpu:8080"]
CONTROLLERS = [
    # (name, image, command, args, port, probe)
    ("kft-operator", PLATFORM_IMAGE,
     ["python", "-m", "kubeflow_tpu.controller"], OPERATOR_ARGS,
     8080, "http"),
    ("metadata-store", PLATFORM_IMAGE,
     ["/opt/kft/native/metadata_store"],
     ["--port", "8081", "--wal", "/data/metadata.wal",
      "--host", "0.0.0.0"],
     8081, "tcp"),
]


def crd(plural: str, kind: str) -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{API_GROUP}"},
        "spec": {
            "group": API_GROUP,
            "names": {"kind": kind, "plural": plural,
                      "singular": kind.lower()},
            "scope": "Namespaced" if kind != "Profile" else "Cluster",
            "versions": [{
                "name": VERSION, "served": True, "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True,
                }},
            }],
        },
    }


def deployment(name: str, image: str, args: list[str],
               namespace: str = "kubeflow-tpu",
               command: Optional[list[str]] = None,
               port: int = 8080, probe: str = "http") -> dict:
    container = {
        "name": name,
        "image": image,
        "args": list(args),
        "ports": [{"containerPort": port, "name": "api"}],
        "volumeMounts": [
            {"name": "state", "mountPath": "/data"},
            {"name": "platform-config", "mountPath": "/etc/kft"},
        ],
        # HTTP components probe /healthz; raw-TCP components (the native
        # metadata store) get a socket check — an httpGet against them
        # would CrashLoopBackOff the pod
        "livenessProbe": (
            {"httpGet": {"path": "/healthz", "port": port}}
            if probe == "http" else
            {"tcpSocket": {"port": port}}),
        "resources": {
            "requests": {"cpu": "100m", "memory": "256Mi"},
            "limits": {"cpu": "2", "memory": "2Gi"},
        },
    }
    if command:
        container["command"] = list(command)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {"app": name}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "serviceAccountName": name,
                    "containers": [container],
                    "volumes": [
                        {"name": "state",
                         "persistentVolumeClaim": {"claimName": f"{name}-state"}},
                        {"name": "platform-config",
                         "configMap": {"name": "kft-platform-config"}},
                    ],
                },
            },
        },
    }


def platform_configmap(namespace: str = "kubeflow-tpu",
                       bootstrap_token: Optional[str] = None) -> dict:
    """The ConfigMap tier the operator's --config flag consumes — generated
    from the REAL PlatformConfig defaults so keys can't drift. The auth
    file ships a bootstrap cluster-admin token (kubeadm-style: random per
    render, never a shared constant; rotate after install) — an empty
    token map would lock every API call out of a fresh install."""
    import dataclasses as dc
    import json as _json
    import secrets

    from kubeflow_tpu.platform.config import PlatformConfig

    if bootstrap_token is None:
        bootstrap_token = "bootstrap-" + secrets.token_hex(16)

    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": "kft-platform-config", "namespace": namespace},
        "data": {"platform.json": _json.dumps(
            dc.asdict(PlatformConfig(state_dir="/data")), indent=2),
            "auth.json": _json.dumps({
                "tokens": {bootstrap_token: "bootstrap-admin@install"},
                "admins": ["bootstrap-admin@install"]})},
    }


def metadata_store_network_policy(namespace: str = "kubeflow-tpu") -> dict:
    """The unauthenticated raw-TCP store binds beyond loopback so kubelet
    can probe it — this policy is what keeps every tenant pod from reading
    or rewriting cross-namespace lineage/HPO/pipeline state: only the
    operator may connect."""
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {"name": "metadata-store-operator-only",
                     "namespace": namespace},
        "spec": {
            "podSelector": {"matchLabels": {"app": "metadata-store"}},
            "policyTypes": ["Ingress"],
            "ingress": [{
                "from": [{"podSelector":
                          {"matchLabels": {"app": "kft-operator"}}}],
                "ports": [{"protocol": "TCP", "port": 8081}],
            }],
        },
    }


def pvc(name: str, namespace: str = "kubeflow-tpu",
        size: str = "10Gi", access: str = "ReadWriteOnce") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"accessModes": [access],
                 "resources": {"requests": {"storage": size}}},
    }


def service(name: str, port: int = 8080,
            namespace: str = "kubeflow-tpu") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"selector": {"app": name},
                 "ports": [{"port": port, "targetPort": port}]},
    }


def rbac(name: str, namespace: str = "kubeflow-tpu") -> list[dict]:
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": name, "namespace": namespace}},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRole",
         "metadata": {"name": name},
         "rules": [
             {"apiGroups": [API_GROUP], "resources": ["*"],
              "verbs": ["*"]},
             {"apiGroups": [""],
              "resources": ["pods", "services", "events", "configmaps"],
              "verbs": ["*"]},
         ]},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": name},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole", "name": name},
         "subjects": [{"kind": "ServiceAccount", "name": name,
                       "namespace": namespace}]},
    ]


def tpu_worker_pod_template(accelerator: str = "v5p",
                            topology: str = "2x2x1") -> dict:
    """The GKE TPU scheduling contract (BASELINE.md): topology node
    selectors + google.com/tpu resource — never nvidia.com/gpu."""
    return {
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": f"tpu-{accelerator}",
            "cloud.google.com/gke-tpu-topology": topology,
        },
        "containers": [{
            "name": "worker",
            "resources": {"limits": {"google.com/tpu": "4"},
                          "requests": {"google.com/tpu": "4"}},
        }],
    }


def render_platform(namespace: str = "kubeflow-tpu",
                    overlays: Optional[list] = None) -> str:
    """The single-apply install document. ``overlays`` are callables
    mutating the doc list (kustomize-patch equivalents)."""
    docs: list[dict] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": namespace}},
    ]
    for plural, kind in CRD_KINDS:
        docs.append(crd(plural, kind))
    docs.append(platform_configmap(namespace))
    docs.append(metadata_store_network_policy(namespace))
    for name, image, command, args, port, probe in CONTROLLERS:
        docs.extend(rbac(name, namespace))
        docs.append(pvc(f"{name}-state", namespace))
        docs.append(deployment(name, image, args, namespace,
                               command=command, port=port, probe=probe))
        docs.append(service(name, port, namespace))
    docs = copy.deepcopy(docs)
    for overlay in overlays or []:
        overlay(docs)
    _assert_no_gpu(docs)
    return yaml.safe_dump_all(docs, sort_keys=False)


def _assert_no_gpu(docs: list[dict]) -> None:
    text = yaml.safe_dump_all(docs)
    for needle in ("nvidia.com/gpu", "nvidia-device-plugin", "runtimeClass"):
        if needle in text:
            raise ValueError(
                f"GPU dependency {needle!r} leaked into the TPU install")


# ---------------------------------------------------------- overlays ----

def overlay_images(mapping: dict[str, str]):
    """Retag images (the kustomize `images:` transformer)."""

    def apply(docs: list[dict]) -> None:
        for doc in docs:
            if doc.get("kind") != "Deployment":
                continue
            for c in doc["spec"]["template"]["spec"]["containers"]:
                if c["image"] in mapping:
                    c["image"] = mapping[c["image"]]

    return apply


def overlay_replicas(app: str, replicas: int):
    def apply(docs: list[dict]) -> None:
        for doc in docs:
            if doc.get("kind") == "Deployment" and \
                    doc["metadata"]["name"] == app:
                doc["spec"]["replicas"] = replicas

    return apply
