"""Install manifests — the `kubectl apply -k example/` equivalent
(SURVEY.md §2.6 'manifests distribution', §3.5 bring-up): render the whole
platform as Kubernetes YAML with ZERO GPU dependencies (BASELINE.md: no
NVIDIA device plugin / runtime class anywhere in the default install).

``render_platform()`` returns the multi-doc YAML; overlays mutate the base
(kustomize-style patches) without touching it.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

import yaml

API_GROUP = "kubeflow-tpu.org"
VERSION = "v1"

CRD_KINDS = [
    ("jaxjobs", "JAXJob"),
    ("tfjobs", "TFJob"),
    ("experiments", "Experiment"),
    ("trials", "Trial"),
    ("inferenceservices", "InferenceService"),
    ("servingruntimes", "ServingRuntime"),
    ("inferencegraphs", "InferenceGraph"),
    ("trainedmodels", "TrainedModel"),
    ("pipelines", "Pipeline"),
    ("pipelineruns", "PipelineRun"),
    ("recurringruns", "RecurringRun"),
    ("profiles", "Profile"),
    ("poddefaults", "PodDefault"),
    ("notebooks", "Notebook"),
    ("tensorboards", "TensorBoard"),
]

CONTROLLERS = [
    # (name, image, args, needs_webhook)
    ("training-controller", "kubeflow-tpu/controller:latest",
     ["--enable-kind=JAXJob", "--enable-kind=TFJob",
      "--gang-scheduler=builtin"], True),
    ("hpo-controller", "kubeflow-tpu/controller:latest",
     ["--enable-kind=Experiment"], True),
    ("serving-controller", "kubeflow-tpu/controller:latest",
     ["--enable-kind=InferenceService"], True),
    ("pipelines-apiserver", "kubeflow-tpu/pipelines:latest", [], False),
    ("metadata-store", "kubeflow-tpu/metadata-store:latest",
     ["--port", "8081", "--wal", "/data/metadata.wal"], False),
    ("dashboard", "kubeflow-tpu/dashboard:latest", [], False),
]


def crd(plural: str, kind: str) -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{API_GROUP}"},
        "spec": {
            "group": API_GROUP,
            "names": {"kind": kind, "plural": plural,
                      "singular": kind.lower()},
            "scope": "Namespaced" if kind != "Profile" else "Cluster",
            "versions": [{
                "name": VERSION, "served": True, "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True,
                }},
            }],
        },
    }


def deployment(name: str, image: str, args: list[str],
               namespace: str = "kubeflow-tpu") -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {"app": name}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "serviceAccountName": name,
                    "containers": [{
                        "name": name,
                        "image": image,
                        "args": list(args),
                        "ports": [{"containerPort": 8080, "name": "metrics"}],
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "256Mi"},
                            "limits": {"cpu": "2", "memory": "2Gi"},
                        },
                    }],
                },
            },
        },
    }


def service(name: str, port: int = 8080,
            namespace: str = "kubeflow-tpu") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"selector": {"app": name},
                 "ports": [{"port": port, "targetPort": port}]},
    }


def rbac(name: str, namespace: str = "kubeflow-tpu") -> list[dict]:
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": name, "namespace": namespace}},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRole",
         "metadata": {"name": name},
         "rules": [
             {"apiGroups": [API_GROUP], "resources": ["*"],
              "verbs": ["*"]},
             {"apiGroups": [""],
              "resources": ["pods", "services", "events", "configmaps"],
              "verbs": ["*"]},
         ]},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": name},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole", "name": name},
         "subjects": [{"kind": "ServiceAccount", "name": name,
                       "namespace": namespace}]},
    ]


def tpu_worker_pod_template(accelerator: str = "v5p",
                            topology: str = "2x2x1") -> dict:
    """The GKE TPU scheduling contract (BASELINE.md): topology node
    selectors + google.com/tpu resource — never nvidia.com/gpu."""
    return {
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": f"tpu-{accelerator}",
            "cloud.google.com/gke-tpu-topology": topology,
        },
        "containers": [{
            "name": "worker",
            "resources": {"limits": {"google.com/tpu": "4"},
                          "requests": {"google.com/tpu": "4"}},
        }],
    }


def render_platform(namespace: str = "kubeflow-tpu",
                    overlays: Optional[list] = None) -> str:
    """The single-apply install document. ``overlays`` are callables
    mutating the doc list (kustomize-patch equivalents)."""
    docs: list[dict] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": namespace}},
    ]
    for plural, kind in CRD_KINDS:
        docs.append(crd(plural, kind))
    for name, image, args, _webhook in CONTROLLERS:
        docs.extend(rbac(name, namespace))
        docs.append(deployment(name, image, args, namespace))
        docs.append(service(name, 8080, namespace))
    docs = copy.deepcopy(docs)
    for overlay in overlays or []:
        overlay(docs)
    _assert_no_gpu(docs)
    return yaml.safe_dump_all(docs, sort_keys=False)


def _assert_no_gpu(docs: list[dict]) -> None:
    text = yaml.safe_dump_all(docs)
    for needle in ("nvidia.com/gpu", "nvidia-device-plugin", "runtimeClass"):
        if needle in text:
            raise ValueError(
                f"GPU dependency {needle!r} leaked into the TPU install")


# ---------------------------------------------------------- overlays ----

def overlay_images(mapping: dict[str, str]):
    """Retag images (the kustomize `images:` transformer)."""

    def apply(docs: list[dict]) -> None:
        for doc in docs:
            if doc.get("kind") != "Deployment":
                continue
            for c in doc["spec"]["template"]["spec"]["containers"]:
                if c["image"] in mapping:
                    c["image"] = mapping[c["image"]]

    return apply


def overlay_replicas(app: str, replicas: int):
    def apply(docs: list[dict]) -> None:
        for doc in docs:
            if doc.get("kind") == "Deployment" and \
                    doc["metadata"]["name"] == app:
                doc["spec"]["replicas"] = replicas

    return apply
