"""PodDefaults — label-selected pod mutation (SURVEY.md §2.6
admission-webhook: inject volumes/env/tolerations into matching pods; how
notebooks and jobs pick up secrets and TPU settings without per-job spec
plumbing)."""

from __future__ import annotations

import dataclasses

from kubeflow_tpu.controller.cluster import Pod


@dataclasses.dataclass
class PodDefault:
    name: str
    namespace: str
    selector: dict[str, str]               # pod labels that opt in
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    volumes: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)

    def matches(self, pod: Pod) -> bool:
        if pod.namespace != self.namespace:
            return False
        return all(pod.labels.get(k) == v for k, v in self.selector.items())


class PodDefaultsRegistry:
    """Holds PodDefaults and applies them — the mutating-webhook role.
    Controllers call ``mutate(pod)`` before creating pods (the JobController
    takes this as its ``pod_mutator`` hook)."""

    def __init__(self):
        self._defaults: dict[tuple[str, str], PodDefault] = {}

    def apply(self, pd: PodDefault) -> None:
        self._defaults[(pd.namespace, pd.name)] = pd

    def delete(self, namespace: str, name: str) -> None:
        self._defaults.pop((namespace, name), None)

    def mutate(self, pod: Pod) -> Pod:
        for pd in self._defaults.values():
            if pd.matches(pod):
                # pod's own values win over injected defaults
                pod.env = {**pd.env, **pod.env}
        return pod
