"""TLS bootstrap — the cert-manager role (SURVEY.md §1 L1), minimized.

The reference's L1 runs cert-manager to issue serving certificates for
webhooks and ingress. The single-binary equivalent: the operator
self-bootstraps a self-signed serving certificate into its state
directory on first boot (``--tls-dir``) and serves its API over HTTPS;
clients pin the generated cert (it is its own CA). Swapping in real
PKI = dropping an issued cert.pem/key.pem into the same directory.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from typing import Sequence


def ensure_self_signed(
    tls_dir: str,
    common_name: str = "kft-operator",
    hostnames: Sequence[str] = ("localhost",),
    ip_sans: Sequence[str] = ("127.0.0.1", "0.0.0.0"),
    days: int = 365,
) -> tuple[str, str]:
    """Return (cert_path, key_path), generating a self-signed pair if the
    directory doesn't already hold one (idempotent across restarts)."""
    os.makedirs(tls_dir, exist_ok=True)
    cert_path = os.path.join(tls_dir, "cert.pem")
    key_path = os.path.join(tls_dir, "key.pem")
    if os.path.exists(cert_path) and os.path.exists(key_path):
        if _sans_cover(cert_path, hostnames, ip_sans):
            return cert_path, key_path
        # a rescheduled pod / changed bind host needs new SANs — silently
        # reusing the old cert would fail every pinning client's hostname
        # check with no hint
        print(f"certs: regenerating {cert_path}: existing SANs do not "
              f"cover {list(hostnames)} + {list(ip_sans)}", flush=True)
        os.unlink(cert_path)
        os.unlink(key_path)

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    sans = [x509.DNSName(h) for h in hostnames]
    sans += [x509.IPAddress(ipaddress.ip_address(ip)) for ip in ip_sans]
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(
            x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(key, hashes.SHA256())
    )
    # 0600 from birth: never a window where the key is world-readable
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return cert_path, key_path


def _sans_cover(cert_path: str, hostnames: Sequence[str],
                ip_sans: Sequence[str]) -> bool:
    try:
        from cryptography import x509

        with open(cert_path, "rb") as f:
            cert = x509.load_pem_x509_certificate(f.read())
        ext = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        have_dns = set(ext.get_values_for_type(x509.DNSName))
        have_ips = {str(ip) for ip in ext.get_values_for_type(x509.IPAddress)}
    except Exception:
        return False
    return set(hostnames) <= have_dns and set(ip_sans) <= have_ips
