"""Profiles + access management — multi-tenancy (SURVEY.md §2.6).

The reference's profile-controller + KFAM: a Profile owns a namespace,
RBAC role bindings for its owner/contributors, and resource quotas. TPU
twist: quotas meter TPU chips by topology (`google.com/tpu`), never GPUs.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class Role(str, enum.Enum):
    OWNER = "owner"
    CONTRIBUTOR = "contributor"
    VIEWER = "viewer"

# capability sets per role (the RBAC ClusterRole equivalents)
ROLE_VERBS = {
    Role.OWNER: {"get", "list", "create", "update", "delete", "manage-access"},
    Role.CONTRIBUTOR: {"get", "list", "create", "update", "delete"},
    Role.VIEWER: {"get", "list"},
}


@dataclasses.dataclass
class ResourceQuota:
    cpu: Optional[str] = None
    memory: Optional[str] = None
    tpu_chips: Optional[int] = None        # google.com/tpu total
    max_jobs: Optional[int] = None
    max_notebooks: Optional[int] = None


@dataclasses.dataclass
class Profile:
    name: str                  # also the namespace name
    owner: str                 # user email
    quota: ResourceQuota = dataclasses.field(default_factory=ResourceQuota)
    contributors: dict[str, Role] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Namespace:
    name: str
    labels: dict[str, str]
    role_bindings: dict[str, Role]         # user -> role
    quota: ResourceQuota


class ProfileController:
    """Reconciles Profiles into namespaces + bindings + quotas, and answers
    access checks (the KFAM API role)."""

    def __init__(self):
        self.profiles: dict[str, Profile] = {}
        self.namespaces: dict[str, Namespace] = {}

    def apply(self, profile: Profile) -> Namespace:
        self.profiles[profile.name] = profile
        bindings = {profile.owner: Role.OWNER}
        bindings.update(profile.contributors)
        ns = Namespace(
            name=profile.name,
            labels={"kubeflow-tpu.org/profile": profile.name,
                    "istio-injection": "enabled"},
            role_bindings=bindings,
            quota=profile.quota,
        )
        self.namespaces[profile.name] = ns
        return ns

    def delete(self, name: str) -> None:
        self.profiles.pop(name, None)
        self.namespaces.pop(name, None)

    # ------------- KFAM-equivalent access API -------------

    def add_contributor(self, profile: str, user: str,
                        role: Role = Role.CONTRIBUTOR,
                        requester: Optional[str] = None) -> None:
        p = self.profiles[profile]
        if requester is not None and not self.can(requester, profile,
                                                  "manage-access"):
            raise PermissionError(
                f"{requester} cannot manage access on {profile}")
        p.contributors[user] = role
        self.apply(p)

    def remove_contributor(self, profile: str, user: str,
                           requester: Optional[str] = None) -> None:
        p = self.profiles[profile]
        if requester is not None and not self.can(requester, profile,
                                                  "manage-access"):
            raise PermissionError(
                f"{requester} cannot manage access on {profile}")
        p.contributors.pop(user, None)
        self.apply(p)

    def can(self, user: str, namespace: str, verb: str) -> bool:
        ns = self.namespaces.get(namespace)
        if ns is None:
            return False
        role = ns.role_bindings.get(user)
        return role is not None and verb in ROLE_VERBS[role]

    def namespaces_for(self, user: str) -> list[str]:
        return sorted(
            ns.name for ns in self.namespaces.values()
            if user in ns.role_bindings
        )

    # ------------- quota checks -------------

    def check_quota(self, namespace: str, *, tpu_chips: int = 0,
                    jobs_running: int = 0, notebooks_running: int = 0,
                    new_jobs: int = 0, new_notebooks: int = 0,
                    new_tpu_chips: int = 0) -> None:
        ns = self.namespaces.get(namespace)
        if ns is None:
            return
        q = ns.quota
        if q.tpu_chips is not None and tpu_chips + new_tpu_chips > q.tpu_chips:
            raise QuotaExceeded(
                f"{namespace}: TPU chip quota {q.tpu_chips} exceeded "
                f"({tpu_chips}+{new_tpu_chips})")
        if q.max_jobs is not None and jobs_running + new_jobs > q.max_jobs:
            raise QuotaExceeded(
                f"{namespace}: job quota {q.max_jobs} exceeded")
        if q.max_notebooks is not None and \
                notebooks_running + new_notebooks > q.max_notebooks:
            raise QuotaExceeded(
                f"{namespace}: notebook quota {q.max_notebooks} exceeded")


class QuotaExceeded(RuntimeError):
    pass
