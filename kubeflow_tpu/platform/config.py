"""Layered platform configuration (SURVEY.md §5 'Config / flag system').

The reference layers: compiled defaults < platform ConfigMaps < binary
flags. Same three tiers here: ``PlatformConfig`` dataclass defaults <
a JSON config file (the ConfigMap role; hot-reloadable by mtime) <
explicit CLI flag overrides. The operator consumes one resolved object.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional


@dataclasses.dataclass
class PlatformConfig:
    # operator loops
    reconcile_period: float = 0.25
    heartbeat_period: float = 1.0
    heartbeat_timeout_s: float = 60.0
    startup_grace_s: float = 300.0
    serving_period: float = 1.0
    # gang scheduling
    gang_aging_s: float = 300.0
    # warm pool (kube backend; controller/warmpool.py): target number of
    # pre-warmed standby zygote pods kept per pool class (0 = disabled),
    # the class keys to maintain, and how old a standby/consumed pod may
    # grow before it is reaped and replaced
    warm_pool_size: int = 0
    warm_pool_classes: list[str] = dataclasses.field(
        default_factory=lambda: ["default"])
    warm_pool_reap_s: float = 600.0
    # paths
    state_dir: str = "/tmp/kft-state"
    log_dir: str = "/tmp/kft-pods"
    heartbeat_dir: str = "/tmp/kft-heartbeats"
    # serving defaults
    default_max_batch: int = 8
    default_max_seq: int = 1024

    def merged(self, overrides: dict[str, Any]) -> "PlatformConfig":
        """New config with non-None overrides applied (flag tier)."""
        known = {f.name for f in dataclasses.fields(self)}
        clean = {k: v for k, v in overrides.items()
                 if k in known and v is not None}
        return dataclasses.replace(self, **clean)


def load_config(path: Optional[str] = None,
                overrides: Optional[dict[str, Any]] = None) -> PlatformConfig:
    """defaults < file (ConfigMap tier) < overrides (flag tier).
    Unknown file keys fail loudly — a typo'd ConfigMap must not silently
    fall back to defaults."""
    cfg = PlatformConfig()
    if path:
        with open(path) as f:
            data = json.load(f)
        known = {f.name for f in dataclasses.fields(PlatformConfig)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"unknown config keys {sorted(unknown)}; known: "
                f"{sorted(known)}")
        cfg = dataclasses.replace(cfg, **data)
    if overrides:
        cfg = cfg.merged(overrides)
    return cfg


class ConfigWatcher:
    """Mtime-based hot reload of the file tier (the ConfigMap-update role).
    ``poll()`` returns the new config when the file changed, else None."""

    def __init__(self, path: str, overrides: Optional[dict] = None):
        self.path = path
        self.overrides = overrides or {}
        self._mtime = self._stat()
        self.current = load_config(path, self.overrides)

    def _stat(self) -> float:
        try:
            return os.path.getmtime(self.path)
        except OSError:
            return 0.0

    def poll(self) -> Optional[PlatformConfig]:
        m = self._stat()
        if m != self._mtime:
            self._mtime = m
            self.current = load_config(self.path, self.overrides)
            return self.current
        return None
