"""Authentication + authorization for the operator API (L1 role).

The reference fronts every surface with istio ingress + dex/oauth2-proxy
(OIDC) and enforces authz with istio AuthorizationPolicies driven by KFAM
(SURVEY.md §1 L1, §2.6). This environment has no OIDC provider, so —
recorded substitution — authentication is bearer-token (static token →
user map, the kubeconfig-token model), and authorization reuses the
ProfileController's KFAM `can(user, namespace, verb)` with a
cluster-admin override. The operator enforces both on every namespaced
HTTP route; /healthz and /metrics stay open (probe/scrape convention).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from kubeflow_tpu.platform.profiles import ProfileController


@dataclasses.dataclass
class AuthResult:
    user: Optional[str]          # None = unauthenticated
    allowed: bool
    status: int                  # 200 / 401 / 403
    reason: str = ""


class Auth:
    """Bearer-token authn + profile-based authz, as one middleware object.

    ``tokens``: token -> user. ``admins``: users allowed every verb in every
    namespace (the cluster-admin ClusterRoleBinding role). ``profiles``: the
    ProfileController whose owner/contributor/viewer bindings gate
    namespaced access.
    """

    VERB_BY_METHOD = {"GET": "get", "POST": "create", "DELETE": "delete",
                      "PUT": "update", "PATCH": "update"}

    def __init__(self, tokens: dict[str, str],
                 profiles: Optional[ProfileController] = None,
                 admins: tuple = ()):
        self.tokens = dict(tokens)
        self.profiles = profiles
        self.admins = set(admins)

    @classmethod
    def from_file(cls, path: str,
                  profiles: Optional[ProfileController] = None) -> "Auth":
        """JSON: {"tokens": {token: user}, "admins": [user],
        "profiles": [{"name": ns, "owner": user, "contributors": [user],
                      "quota": {"tpu_chips": N, "max_jobs": N, ...}}]}."""
        with open(path) as f:
            spec = json.load(f)
        if profiles is None and spec.get("profiles"):
            import dataclasses as _dc

            from kubeflow_tpu.platform.profiles import Profile, ResourceQuota

            quota_keys = {f.name for f in _dc.fields(ResourceQuota)}
            profiles = ProfileController()
            for p in spec["profiles"]:
                quota = p.get("quota", {})
                unknown = set(quota) - quota_keys
                if unknown:
                    raise ValueError(
                        f"profile {p['name']!r} in {path}: unknown quota "
                        f"keys {sorted(unknown)}; known: "
                        f"{sorted(quota_keys)}")
                prof = Profile(name=p["name"], owner=p["owner"],
                               quota=ResourceQuota(**quota))
                profiles.apply(prof)
                for c in p.get("contributors", []):
                    profiles.add_contributor(p["name"], c)
        return cls(spec.get("tokens", {}), profiles,
                   tuple(spec.get("admins", ())))

    def authenticate(self, authorization: Optional[str]) -> Optional[str]:
        if not authorization or not authorization.startswith("Bearer "):
            return None
        return self.tokens.get(authorization[len("Bearer "):].strip())

    def check(self, authorization: Optional[str], method: str,
              namespace: Optional[str]) -> AuthResult:
        user = self.authenticate(authorization)
        if user is None:
            return AuthResult(None, False, 401, "missing or invalid token")
        if user in self.admins:
            return AuthResult(user, True, 200)
        verb = self.VERB_BY_METHOD.get(method, "get")
        if namespace is None:
            # namespaced resource path not matched: let the route handler
            # 404; authenticated users may probe paths
            return AuthResult(user, True, 200)
        if self.profiles is not None and \
                self.profiles.can(user, namespace, verb):
            return AuthResult(user, True, 200)
        return AuthResult(
            user, False, 403,
            f"user {user!r} may not {verb} in namespace {namespace!r}")
