"""Platform shell — multi-tenancy, pod defaults, notebooks, dashboard,
install manifests (SURVEY.md §2.6, §7 step 8)."""

from kubeflow_tpu.platform.dashboard import Dashboard
from kubeflow_tpu.platform.manifests import (
    overlay_images, overlay_replicas, render_platform,
    tpu_worker_pod_template,
)
from kubeflow_tpu.platform.notebooks import (
    Notebook, NotebookController, TensorBoard, TensorBoardController,
)
from kubeflow_tpu.platform.poddefaults import PodDefault, PodDefaultsRegistry
from kubeflow_tpu.platform.profiles import (
    Profile, ProfileController, QuotaExceeded, ResourceQuota, Role,
)

__all__ = [
    "Dashboard", "Notebook", "NotebookController", "PodDefault",
    "PodDefaultsRegistry", "Profile", "ProfileController", "QuotaExceeded",
    "ResourceQuota", "Role", "TensorBoard", "TensorBoardController",
    "overlay_images", "overlay_replicas", "render_platform",
    "tpu_worker_pod_template",
]
