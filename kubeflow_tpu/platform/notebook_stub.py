"""Minimal notebook server for image-less backends.

On a real cluster a Notebook pod's container image (jupyter) provides the
entrypoint, so the controller leaves ``command`` empty and kubelet runs
the image. `LocalProcessCluster` has no images — an empty command would
exit immediately and the notebook would never be Running. This stub is
the local stand-in entrypoint: a live HTTP server on ``KFT_BIND`` with a
jupyter-shaped liveness surface (``/api`` -> version JSON, ``/`` -> a
placeholder page), enough for the controller's Running state, the
service's endpoint resolution, and the culling clock to be real.
"""

from __future__ import annotations

import json
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def main() -> int:
    bind = os.environ.get("KFT_BIND", "127.0.0.1:8888")
    host, _, port = bind.rpartition(":")
    name = os.environ.get("KFT_NOTEBOOK_NAME", "notebook")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.startswith("/api"):
                body = json.dumps({"version": "kft-notebook-stub",
                                   "name": name}).encode()
                ctype = "application/json"
            else:
                body = (f"<html><body><h1>{name}</h1>"
                        "<p>kubeflow-tpu notebook (local backend stub — "
                        "a real deployment runs the notebook image "
                        "entrypoint here)</p></body></html>").encode()
                ctype = "text/html"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
    print(f"notebook stub serving on {bind}", flush=True)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
