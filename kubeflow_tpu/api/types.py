"""Job API types — the CRD surface of the training layer.

Capability parity with the reference training-operator's API types
(SURVEY.md §2.1: `TFJob`/`PyTorchJob`/... with shared `RunPolicy`,
`ReplicaSpec`, `JobStatus`, `JobCondition`), redesigned TPU-first:

- `JAXJob` is the PRIMARY kind (the reference has none — BASELINE.json:5's
  north star is adding it). Replicas request TPU *slices* by topology
  (`TPUSpec`), not GPU counts.
- Rendezvous is jax.distributed over ICI/DCN: the controller computes
  coordinator address + process ids (SURVEY.md §2.8) — no MASTER_ADDR/NCCL.
- Specs are plain dataclasses with YAML round-trip, so the same objects are
  a Python SDK surface AND a kubectl-style file format.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Optional

import yaml


class RestartPolicy(str, enum.Enum):
    NEVER = "Never"
    ON_FAILURE = "OnFailure"
    ALWAYS = "Always"
    EXIT_CODE = "ExitCode"   # restart only on retryable exit codes (128+)


class CleanPodPolicy(str, enum.Enum):
    RUNNING = "Running"
    ALL = "All"
    NONE = "None"


class ConditionType(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SUSPENDED = "Suspended"
    # advisory, never a phase: degraded-but-running signals from workers
    # (e.g. a dead checkpoint mirror). Skipped by condition()/is_finished().
    WARNING = "Warning"


class ReplicaType(str, enum.Enum):
    COORDINATOR = "Coordinator"   # process 0 / rendezvous anchor
    WORKER = "Worker"
    # TFJob-compat roles (CPU baseline config, BASELINE.json:7)
    CHIEF = "Chief"
    PS = "PS"
    EVALUATOR = "Evaluator"
    # PyTorchJob/XGBoostJob-compat role (rank-0 / tracker anchor)
    MASTER = "Master"


@dataclasses.dataclass
class TPUSpec:
    """TPU slice request — replaces `nvidia.com/gpu: N` resource requests
    with topology-first slice selection (BASELINE.json:5)."""

    accelerator: str = "v5p"          # gke-tpu-accelerator selector value
    topology: str = "2x2x1"           # gke-tpu-topology selector value
    chips_per_host: int = 4

    @property
    def num_chips(self) -> int:
        dims = [int(x) for x in self.topology.split("x")]
        n = 1
        for d in dims:
            n *= d
        return n

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)


@dataclasses.dataclass
class PodTemplate:
    image: str = "kubeflow-tpu/runtime:latest"
    command: list[str] = dataclasses.field(default_factory=list)
    args: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    cpu: str = "4"
    memory: str = "16Gi"
    tpu: Optional[TPUSpec] = None
    volumes: dict[str, str] = dataclasses.field(default_factory=dict)  # name->mount


@dataclasses.dataclass
class ReplicaSpec:
    replicas: int = 1
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE
    template: PodTemplate = dataclasses.field(default_factory=PodTemplate)


@dataclasses.dataclass
class SchedulingPolicy:
    gang: bool = True                  # all-or-nothing (whole slice) placement
    queue: str = "default"
    priority: int = 0
    min_available: Optional[int] = None   # defaults to total replicas


@dataclasses.dataclass
class RunPolicy:
    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.RUNNING
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: int = 3
    scheduling: SchedulingPolicy = dataclasses.field(default_factory=SchedulingPolicy)
    suspend: bool = False


@dataclasses.dataclass
class Condition:
    type: ConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclasses.dataclass
class JobStatus:
    conditions: list[Condition] = dataclasses.field(default_factory=list)
    replica_statuses: dict[str, ReplicaStatus] = dataclasses.field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    restart_count: int = 0
    # elastic recovery (per-worker replacement instead of whole-gang
    # restart): total warm replacements performed for this job, per-worker
    # replacement counts (the per-worker backoff/budget accounting — keys
    # are job pod identities like "job-worker-1"), and the rendezvous
    # epoch every pod of the CURRENT worker-incarnation carries as
    # KFT_RENDEZVOUS_EPOCH (bumped on every replacement or gang restart
    # so survivors and replacements agree on which world they re-form)
    worker_replacements: int = 0
    replacement_counts: dict[str, int] = dataclasses.field(
        default_factory=dict)
    rendezvous_epoch: int = 0

    def condition(self) -> Optional[ConditionType]:
        """Latest *phase* condition — Warning entries are advisory and never
        define the job's phase."""
        for c in reversed(self.conditions):
            if c.type != ConditionType.WARNING:
                return c.type
        return None

    def warnings(self) -> list[Condition]:
        return [c for c in self.conditions
                if c.type == ConditionType.WARNING]

    def is_finished(self) -> bool:
        return self.condition() in (ConditionType.SUCCEEDED, ConditionType.FAILED)


@dataclasses.dataclass
class ElasticPolicy:
    """PyTorchJob-compat elastic policy (reference: ElasticPolicy on
    PyTorchJob — torchrun c10d rendezvous with a min/max world size).
    The controller exports it as the PET_* env contract torchrun reads."""

    min_replicas: int = 1
    max_replicas: int = 1
    nproc_per_node: int = 1
    rdzv_backend: str = "c10d"
    max_restarts: int = 3


@dataclasses.dataclass
class JobSpec:
    """Base job: named replica groups + run policy. Kind-specific rendezvous
    env is produced by the controller's `cluster_env()` per kind."""

    name: str = "job"
    namespace: str = "default"
    kind: str = "JAXJob"
    replica_specs: dict[str, ReplicaSpec] = dataclasses.field(default_factory=dict)
    run_policy: RunPolicy = dataclasses.field(default_factory=RunPolicy)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    status: JobStatus = dataclasses.field(default_factory=JobStatus)
    uid: str = ""
    elastic: Optional[ElasticPolicy] = None   # PyTorchJob kinds only

    @property
    def total_replicas(self) -> int:
        return sum(r.replicas for r in self.replica_specs.values())


def jax_job(
    name: str,
    *,
    workers: int = 1,
    tpu: TPUSpec | None = None,
    image: str = "kubeflow-tpu/runtime:latest",
    command: list[str] | None = None,
    env: dict[str, str] | None = None,
    mesh: dict[str, int] | None = None,
    dcn: dict[str, int] | None = None,
    run_policy: RunPolicy | None = None,
    namespace: str = "default",
) -> JobSpec:
    """Build a JAXJob: N worker processes forming one jax.distributed world.

    `mesh`/`dcn` become the KFT_MESH/KFT_DCN env contract consumed by
    `rendezvous.bootstrap` + `parallel.mesh_from_topology_env` in-worker.
    """
    env = dict(env or {})
    if mesh:
        env["KFT_MESH"] = ",".join(f"{k}={v}" for k, v in mesh.items())
    if dcn:
        env["KFT_DCN"] = ",".join(f"{k}={v}" for k, v in dcn.items())
    tmpl = PodTemplate(image=image, command=command or [], env=env, tpu=tpu)
    return JobSpec(
        name=name,
        namespace=namespace,
        kind="JAXJob",
        replica_specs={
            ReplicaType.WORKER.value: ReplicaSpec(replicas=workers, template=tmpl)
        },
        run_policy=run_policy or RunPolicy(),
    )


def pipeline_jax_job(
    name: str,
    *,
    stages: int,
    workers_per_stage: int = 1,
    virtual_stages: int = 1,
    tpu: TPUSpec | None = None,
    image: str = "kubeflow-tpu/runtime:latest",
    command: list[str] | None = None,
    env: dict[str, str] | None = None,
    run_policy: RunPolicy | None = None,
    namespace: str = "default",
) -> JobSpec:
    """Build an MPMD pipeline JAXJob: ``stages`` per-stage worker groups
    gang-scheduled as ONE job (one PodGroup, all-or-nothing admission —
    a pipeline with a missing stage can never make progress, so partial
    placement is wasted capacity). The controller stamps each worker's
    stage rendezvous env (KFT_STAGE_ID / _BIND / _PREV / _NEXT, backed
    by one stable Service per stage) next to the usual JAXJob contract;
    ``rendezvous.bootstrap.stage_from_env`` reads it in-worker. A dead
    stage worker takes the per-worker replacement path (PR 9) — the
    stage Services keep the neighbor addresses valid across it.

    ``virtual_stages`` > 1 requests the interleaved-1F1B schedule: each
    worker owns V model chunks and the controller additionally stamps
    KFT_VIRTUAL_STAGES plus the ring-wrap links (KFT_STAGE_WRAP_NEXT on
    the last stage, KFT_STAGE_WRAP_PREV on stage 0)."""
    if stages < 2:
        raise ValidationError("pipeline_jax_job needs stages >= 2")
    if virtual_stages < 1:
        raise ValidationError("pipeline_jax_job needs virtual_stages >= 1")
    env = dict(env or {})
    env["KFT_NUM_STAGES"] = str(stages)
    if virtual_stages > 1:
        env["KFT_VIRTUAL_STAGES"] = str(virtual_stages)
    return jax_job(
        name, workers=stages * workers_per_stage, tpu=tpu, image=image,
        command=command, env=env, run_policy=run_policy,
        namespace=namespace)


def tf_job(
    name: str,
    *,
    workers: int = 1,
    ps: int = 0,
    chief: bool = False,
    image: str = "kubeflow-tpu/runtime:latest",
    command: list[str] | None = None,
    namespace: str = "default",
) -> JobSpec:
    """TFJob-compatible kind (the CPU baseline config, BASELINE.json:7)."""
    tmpl = lambda: PodTemplate(image=image, command=command or [])
    specs: dict[str, ReplicaSpec] = {}
    if chief:
        specs[ReplicaType.CHIEF.value] = ReplicaSpec(replicas=1, template=tmpl())
    specs[ReplicaType.WORKER.value] = ReplicaSpec(replicas=workers, template=tmpl())
    if ps:
        specs[ReplicaType.PS.value] = ReplicaSpec(replicas=ps, template=tmpl())
    return JobSpec(name=name, namespace=namespace, kind="TFJob", replica_specs=specs)


def pytorch_job(
    name: str,
    *,
    workers: int = 1,
    master: bool = True,
    image: str = "kubeflow-tpu/runtime:latest",
    command: list[str] | None = None,
    env: dict[str, str] | None = None,
    elastic: ElasticPolicy | None = None,
    namespace: str = "default",
) -> JobSpec:
    """PyTorchJob-compatible kind (reference: pkg/controller.v1/pytorch).

    The controller exports the torch.distributed rendezvous contract
    (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK; PET_* when elastic). On TPU
    the same env feeds PyTorch/XLA, whose xla:// init reads it unchanged —
    so one kind serves both CPU-gloo tests and torch-on-TPU."""
    tmpl = lambda: PodTemplate(
        image=image, command=list(command or []), env=dict(env or {}))
    specs: dict[str, ReplicaSpec] = {}
    if master:
        specs[ReplicaType.MASTER.value] = ReplicaSpec(replicas=1, template=tmpl())
    if workers:
        specs[ReplicaType.WORKER.value] = ReplicaSpec(
            replicas=workers, template=tmpl())
    return JobSpec(name=name, namespace=namespace, kind="PyTorchJob",
                   replica_specs=specs, elastic=elastic)


def xgboost_job(
    name: str,
    *,
    workers: int = 1,
    image: str = "kubeflow-tpu/runtime:latest",
    command: list[str] | None = None,
    env: dict[str, str] | None = None,
    namespace: str = "default",
) -> JobSpec:
    """XGBoostJob-compatible kind (reference: pkg/controller.v1/xgboost —
    Rabit tracker rendezvous: MASTER_ADDR/MASTER_PORT + WORLD_SIZE/RANK,
    with the Master replica hosting the tracker)."""
    tmpl = lambda: PodTemplate(
        image=image, command=list(command or []), env=dict(env or {}))
    specs = {
        ReplicaType.MASTER.value: ReplicaSpec(replicas=1, template=tmpl()),
    }
    if workers:
        specs[ReplicaType.WORKER.value] = ReplicaSpec(
            replicas=workers, template=tmpl())
    return JobSpec(name=name, namespace=namespace, kind="XGBoostJob",
                   replica_specs=specs)


# ---------------------------------------------------------------------------
# Validation (the reference's validating-admission-webhook equivalent,
# SURVEY.md §2.1 'Webhooks')
# ---------------------------------------------------------------------------

class ValidationError(ValueError):
    pass


def validate(job: JobSpec) -> None:
    if not job.name or not job.name.replace("-", "").replace(".", "").isalnum():
        raise ValidationError(f"invalid job name {job.name!r}")
    if not job.replica_specs:
        raise ValidationError("job has no replica specs")
    for rtype, spec in job.replica_specs.items():
        if spec.replicas < 1:
            raise ValidationError(f"{rtype}: replicas must be >= 1")
        if rtype not in {t.value for t in ReplicaType}:
            raise ValidationError(f"unknown replica type {rtype!r}")
    if job.kind == "JAXJob":
        if ReplicaType.WORKER.value not in job.replica_specs:
            raise ValidationError("JAXJob requires a Worker replica spec")
        for rtype, spec in job.replica_specs.items():
            t = spec.template
            if t.tpu is not None and t.tpu.num_chips % t.tpu.chips_per_host:
                raise ValidationError(
                    f"{rtype}: topology {t.tpu.topology} not divisible by "
                    f"chips_per_host={t.tpu.chips_per_host}"
                )
        stages_env = _worker_env(job).get("KFT_NUM_STAGES")
        if stages_env:
            try:
                n_stages = int(stages_env)
            except ValueError:
                raise ValidationError(
                    f"KFT_NUM_STAGES must be an int, got {stages_env!r}")
            w = job.replica_specs[ReplicaType.WORKER.value].replicas
            if n_stages < 2:
                raise ValidationError("MPMD pipeline needs >= 2 stages")
            if w % n_stages:
                raise ValidationError(
                    f"workers={w} not divisible by pipeline stages="
                    f"{n_stages} (stage groups must be equal)")
        mesh_env = _worker_env(job).get("KFT_MESH")
        if mesh_env:
            from kubeflow_tpu.parallel.mesh import AXIS_ORDER

            for part in mesh_env.split(","):
                axis = part.split("=")[0]
                if axis not in AXIS_ORDER:
                    raise ValidationError(f"unknown mesh axis {axis!r} in KFT_MESH")
    if job.elastic is not None and job.kind != "PyTorchJob":
        raise ValidationError(f"elastic policy is not valid for kind {job.kind}")
    if job.kind in ("PyTorchJob", "XGBoostJob"):
        m = job.replica_specs.get(ReplicaType.MASTER.value)
        if m is not None and m.replicas != 1:
            raise ValidationError(f"{job.kind}: Master must have exactly 1 replica")
        if m is None and job.kind == "XGBoostJob":
            raise ValidationError("XGBoostJob requires a Master replica spec")
        if m is None and ReplicaType.WORKER.value not in job.replica_specs:
            raise ValidationError(
                f"{job.kind} requires a Master or Worker replica spec")
        if job.elastic is not None:
            e = job.elastic
            if not (1 <= e.min_replicas <= e.max_replicas):
                raise ValidationError(
                    "elastic: need 1 <= min_replicas <= max_replicas")
            if e.nproc_per_node < 1 or e.max_restarts < 0:
                raise ValidationError(
                    "elastic: need nproc_per_node >= 1 and max_restarts >= 0")
    sched = job.run_policy.scheduling
    if sched.min_available is not None and sched.min_available > job.total_replicas:
        raise ValidationError(
            f"min_available {sched.min_available} > total replicas "
            f"{job.total_replicas}"
        )


def _worker_env(job: JobSpec) -> dict[str, str]:
    w = job.replica_specs.get(ReplicaType.WORKER.value)
    return w.template.env if w else {}


# ---------------------------------------------------------------------------
# YAML round-trip
# ---------------------------------------------------------------------------

def _to_plain(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _to_plain(getattr(obj, f.name)) for f in dataclasses.fields(obj)
        }
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: _to_plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_plain(v) for v in obj]
    return obj


def to_yaml(job: JobSpec) -> str:
    doc = {
        "apiVersion": "kubeflow-tpu.org/v1",
        "kind": job.kind,
        "metadata": {"name": job.name, "namespace": job.namespace,
                     "labels": job.labels},
        "spec": {
            "replicaSpecs": {
                k: _to_plain(v) for k, v in job.replica_specs.items()
            },
            "runPolicy": _to_plain(job.run_policy),
        },
    }
    if job.elastic is not None:
        doc["spec"]["elasticPolicy"] = _to_plain(job.elastic)
    if job.uid:
        doc["metadata"]["uid"] = job.uid
    cond = job.status.condition()
    if cond is not None:
        # CR status subresource role: enough state that a restarted
        # controller never re-runs a finished job, never loses its
        # active-deadline/TTL clocks, and keeps its backoff count
        # (replica counts are recomputed from live pod observation)
        doc["status"] = {
            "condition": cond.value,
            "restartCount": job.status.restart_count,
            "startTime": job.status.start_time,
            "completionTime": job.status.completion_time,
        }
        if job.status.worker_replacements or job.status.rendezvous_epoch:
            # a restarted controller must keep the per-worker budget and
            # epoch too, or an adopted flapping worker gets a fresh budget
            doc["status"]["workerReplacements"] = (
                job.status.worker_replacements)
            doc["status"]["rendezvousEpoch"] = job.status.rendezvous_epoch
            doc["status"]["replacementCounts"] = dict(
                job.status.replacement_counts)
    return yaml.safe_dump(doc, sort_keys=False)


def from_yaml(text: str) -> JobSpec:
    doc = yaml.safe_load(text)
    meta = doc.get("metadata", {})
    spec = doc.get("spec", {})

    def mk_tpu(d):
        return TPUSpec(**d) if d else None

    replica_specs = {}
    for rtype, rs in spec.get("replicaSpecs", {}).items():
        t = rs.get("template", {})
        replica_specs[rtype] = ReplicaSpec(
            replicas=rs.get("replicas", 1),
            restart_policy=RestartPolicy(rs.get("restart_policy", "OnFailure")),
            template=PodTemplate(
                image=t.get("image", "kubeflow-tpu/runtime:latest"),
                command=t.get("command", []),
                args=t.get("args", []),
                env=t.get("env", {}),
                cpu=t.get("cpu", "4"),
                memory=t.get("memory", "16Gi"),
                tpu=mk_tpu(t.get("tpu")),
                volumes=t.get("volumes", {}),
            ),
        )
    rp = spec.get("runPolicy", {})
    sched = rp.get("scheduling", {})
    run_policy = RunPolicy(
        clean_pod_policy=CleanPodPolicy(rp.get("clean_pod_policy", "Running")),
        ttl_seconds_after_finished=rp.get("ttl_seconds_after_finished"),
        active_deadline_seconds=rp.get("active_deadline_seconds"),
        backoff_limit=rp.get("backoff_limit", 3),
        scheduling=SchedulingPolicy(
            gang=sched.get("gang", True),
            queue=sched.get("queue", "default"),
            priority=sched.get("priority", 0),
            min_available=sched.get("min_available"),
        ),
        suspend=rp.get("suspend", False),
    )
    ep = spec.get("elasticPolicy")
    elastic = None
    if ep is not None:
        # lenient like the rest of from_yaml: tolerate unknown keys and
        # accept both snake_case and the reference CRD's camelCase
        if not isinstance(ep, dict):
            raise ValidationError("elasticPolicy must be a mapping")

        def _g(snake: str, camel: str, default):
            return ep.get(snake, ep.get(camel, default))

        elastic = ElasticPolicy(
            min_replicas=_g("min_replicas", "minReplicas", 1),
            max_replicas=_g("max_replicas", "maxReplicas", 1),
            nproc_per_node=_g("nproc_per_node", "nProcPerNode", 1),
            rdzv_backend=_g("rdzv_backend", "rdzvBackend", "c10d"),
            max_restarts=_g("max_restarts", "maxRestarts", 3),
        )
    job = JobSpec(
        name=meta.get("name", "job"),
        namespace=meta.get("namespace", "default"),
        kind=doc.get("kind", "JAXJob"),
        replica_specs=replica_specs,
        run_policy=run_policy,
        labels=meta.get("labels", {}),
        elastic=elastic,
        uid=meta.get("uid", ""),
    )
    st = doc.get("status") or {}
    if st.get("condition"):
        job.status.conditions.append(Condition(
            type=ConditionType(st["condition"]), reason="Restored"))
        job.status.restart_count = int(st.get("restartCount", 0))
        job.status.worker_replacements = int(st.get("workerReplacements", 0))
        job.status.rendezvous_epoch = int(st.get("rendezvousEpoch", 0))
        rc = st.get("replacementCounts")
        if isinstance(rc, dict):
            job.status.replacement_counts = {
                str(k): int(v) for k, v in rc.items()}
        if st.get("startTime") is not None:
            job.status.start_time = float(st["startTime"])
        if st.get("completionTime") is not None:
            job.status.completion_time = float(st["completionTime"])
    return job
