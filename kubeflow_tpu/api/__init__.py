from kubeflow_tpu.api.types import (
    CleanPodPolicy, Condition, ConditionType, JobSpec, JobStatus, PodTemplate,
    ReplicaSpec, ReplicaType, RestartPolicy, RunPolicy, SchedulingPolicy,
    TPUSpec, ValidationError, from_yaml, jax_job, tf_job, to_yaml, validate,
)
