from kubeflow_tpu.api.types import (
    CleanPodPolicy, Condition, ConditionType, ElasticPolicy, JobSpec,
    JobStatus, PodTemplate, ReplicaSpec, ReplicaType, RestartPolicy,
    RunPolicy, SchedulingPolicy, TPUSpec, ValidationError, from_yaml,
    jax_job, pytorch_job, tf_job, to_yaml, validate, xgboost_job,
)
