"""Data-plane ingress for serving — the istio-VirtualService / Knative
route role (SURVEY.md §3.3: 'client → Istio ingress gateway → … predictor').

One stable endpoint per platform routes ``/serving/{ns}/{isvc}/<rest>`` to
a live predictor pod, choosing the REVISION per request by the service's
traffic split — so canary percentages are enforced at the data plane, not
just recorded in status. Within a revision, requests spread across its
running predictor pods.

The proxy streams: responses without a Content-Length (SSE token streams,
chunked bodies) are forwarded chunk-by-chunk as they arrive.
"""

from __future__ import annotations

import http.client
import random
from typing import Optional

from kubeflow_tpu.controller.cluster import PodPhase


class IngressGateway:
    """Revision-weighted router over a ServingController's pods.

    With an ``autoscaler``, the gateway also plays the Knative ACTIVATOR:
    a request for a service with no live backend (scaled to zero) wakes
    the autoscaler and holds the request until a pod comes up (the daemon
    ticker applies the scale), up to ``wake_timeout_s``."""

    def __init__(self, controller, seed: int = 0, autoscaler=None,
                 wake_timeout_s: float = 60.0, wake_poll_s: float = 0.2):
        self.controller = controller
        self.autoscaler = autoscaler
        self.wake_timeout_s = wake_timeout_s
        self.wake_poll_s = wake_poll_s
        self._rng = random.Random(seed)

    def pick_backend(self, namespace: str, name: str) -> Optional[str]:
        """-> 'host:port' of a predictor pod chosen by the traffic split,
        or None when the service has no routable backend."""
        isvc = self.controller.get(namespace, name)
        if isvc is None or not isvc.status.traffic:
            return None
        entries = [(rev, w) for rev, w in isvc.status.traffic.items()
                   if w > 0]
        if not entries:
            return None
        revs, weights = zip(*entries)
        # try the drawn revision first, then the rest by weight — a canary
        # with no live pod must not 503 the request the split sent it
        order = sorted(
            revs, key=lambda r: -isvc.status.traffic[r])
        drawn = self._rng.choices(revs, weights=weights)[0]
        order.remove(drawn)
        for rev in [drawn] + order:
            pods = [
                p for p in self.controller._pods(isvc, revision=rev)
                if p.labels.get("component") == "predictor"
                and p.phase == PodPhase.RUNNING and p.env.get("KFT_BIND")
            ]
            if pods:
                return self._rng.choice(pods).env["KFT_BIND"]
        return None

    def _activate(self, namespace: str, name: str) -> Optional[str]:
        """Scale-from-zero on request: wake the autoscaler, keep it awake,
        and wait for a backend (the activator's hold-the-request path).

        Engages ONLY for a service actually scaled to zero — a broken
        service (crash-looping pod, no matching runtime) must keep its
        fast 503, not tie a handler thread up for wake_timeout_s."""
        import time

        isvc = self.controller.get(namespace, name)
        if self.autoscaler is None or isvc is None:
            return None
        if self.controller._predictor_replicas(isvc) != 0:
            return None
        deadline = time.time() + self.wake_timeout_s
        while time.time() < deadline:
            # deleted mid-hold: fail fast and stop re-seeding autoscaler
            # state the controller's delete() has already reset
            if self.controller.get(namespace, name) is None:
                return None
            # re-wake each poll: the cold start may outlast the idle grace
            self.autoscaler.wake(namespace, name)
            backend = self.pick_backend(namespace, name)
            if backend is not None:
                return backend
            time.sleep(self.wake_poll_s)
        return None

    def proxy(self, handler, method: str, namespace: str, name: str,
              rest: str, body: Optional[bytes]) -> None:
        """Forward one request to a chosen backend, streaming the response
        through ``handler`` (a BaseHTTPRequestHandler)."""
        backend = self.pick_backend(namespace, name)
        if backend is None:
            backend = self._activate(namespace, name)
        if backend is None:
            payload = b'{"error": "no ready backend"}'
            handler.send_response(503)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
            return
        host, _, port = backend.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=600)
        try:
            headers = {}
            ctype = handler.headers.get("Content-Type")
            if ctype:
                headers["Content-Type"] = ctype
            accept = handler.headers.get("Accept")
            if accept:
                headers["Accept"] = accept
            conn.request(method, "/" + rest, body=body, headers=headers)
            resp = conn.getresponse()
            handler.proxy_headers_sent = True   # past here, no clean 502
            handler.send_response(resp.status)
            clen = resp.getheader("Content-Length")
            rtype = resp.getheader("Content-Type")
            if rtype:
                handler.send_header("Content-Type", rtype)
            if clen is not None:
                handler.send_header("Content-Length", clen)
                handler.end_headers()
                handler.wfile.write(resp.read())
            else:
                # streaming (SSE / chunked): forward as it arrives. The
                # outer hop re-chunks; token-by-token latency is preserved.
                handler.send_header("Transfer-Encoding", "chunked")
                handler.end_headers()
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    handler.wfile.write(
                        f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    handler.wfile.flush()
                handler.wfile.write(b"0\r\n\r\n")
        finally:
            conn.close()
