"""Storage initializer — model download by storageUri.

Parity: SURVEY.md §2.4 'Storage' (kserve.storage + the agent downloader:
gcs/s3/pvc/http/hf). TPU build keeps the same uri scheme dispatch; schemes
whose SDKs aren't in this environment are gated with a clear error instead
of a hard import.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile

_MARKER = ".kft_materialized.json"


def _marker_path(dest_dir: str) -> str:
    return os.path.join(dest_dir, _MARKER)


def _already_materialized(storage_uri: str, dest_dir: str):
    """Remote downloads are recorded with a marker so the init step and the
    server (which both call download) don't fetch the artifact twice."""
    try:
        with open(_marker_path(dest_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("uri_sha") != _uri_sha(storage_uri):
        return None
    path = doc.get("path")
    return path if path and os.path.exists(path) else None


def _uri_sha(storage_uri: str) -> str:
    return hashlib.sha256(storage_uri.encode()).hexdigest()[:16]


def _record(storage_uri: str, dest_dir: str, path: str) -> str:
    with open(_marker_path(dest_dir), "w") as f:
        json.dump({"uri_sha": _uri_sha(storage_uri), "path": path}, f)
    return path


def download(storage_uri: str, dest_dir: str) -> str:
    """Materialize the model behind ``storage_uri`` into ``dest_dir`` and
    return the local path (the storage-initializer initContainer contract:
    runs before the server starts, mounts at /mnt/models). Idempotent for
    remote schemes: a completed download leaves a marker and later calls
    return immediately."""
    os.makedirs(dest_dir, exist_ok=True)
    parsed = urllib.parse.urlparse(storage_uri)
    scheme = parsed.scheme or "file"
    if scheme == "file":
        return _from_local(parsed.path or storage_uri, dest_dir)
    if scheme == "pvc":
        # pvc://volume/path — volume is mounted at /mnt/pvc/<volume> by the
        # pod webhook; locally this is just a directory
        path = os.path.join("/mnt/pvc", parsed.netloc,
                            parsed.path.lstrip("/"))
        return _from_local(path, dest_dir)
    done = _already_materialized(storage_uri, dest_dir)
    if done is not None:
        return done
    if scheme in ("http", "https"):
        fname = os.path.basename(parsed.path) or "model"
        target = os.path.join(dest_dir, fname)
        urllib.request.urlretrieve(storage_uri, target)
        return _record(storage_uri, dest_dir,
                       _maybe_unpack(target, dest_dir))
    if scheme == "hf":
        return _record(
            storage_uri, dest_dir,
            _from_huggingface(parsed.netloc + parsed.path, dest_dir))
    if scheme in ("gs", "s3", "azure"):
        return _from_mounted_bucket(scheme, parsed, dest_dir)
    raise ValueError(f"unsupported storage uri scheme {scheme!r}")


# Mounted-bucket convention: on GKE the pod webhook mounts buckets with
# FUSE (gcsfuse / s3 mountpoint) under these roots, so gs://bucket/path is
# readable as a plain directory — no cloud SDK in the serving image at all
# (the TPU-native choice: the kernel page cache streams weights, and the
# same path works for every framework). Override the root with
# KFT_BUCKET_MOUNT_ROOT, e.g. in tests.
_BUCKET_MOUNT_ROOTS = {"gs": "/gcs", "s3": "/s3", "azure": "/azure"}


def _from_mounted_bucket(scheme: str, parsed, dest_dir: str) -> str:
    root = os.environ.get("KFT_BUCKET_MOUNT_ROOT",
                          _BUCKET_MOUNT_ROOTS[scheme])
    path = os.path.normpath(
        os.path.join(root, parsed.netloc, parsed.path.lstrip("/")))
    # storage_uri is tenant-supplied: ".." must never escape the mount root
    # (gs://../etc would otherwise resolve to /etc)
    if not path.startswith(os.path.normpath(root) + os.sep):
        raise ValueError(
            f"storage uri escapes the {scheme} mount root: {path!r}")
    if not os.path.exists(path):
        raise RuntimeError(
            f"{scheme}://{parsed.netloc} is not mounted at {root} (expected "
            f"{path}); mount the bucket (gcsfuse/mountpoint via the pod "
            f"webhook) or mirror the model to file://")
    return _from_local(path, dest_dir)


def _from_local(path: str, dest_dir: str) -> str:
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if os.path.isdir(path):
        return path          # serve in place; no copy needed
    return _maybe_unpack(path, dest_dir, copy=True)


def _maybe_unpack(path: str, dest_dir: str, copy: bool = False) -> str:
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as tf:
            tf.extractall(dest_dir, filter="data")
        return dest_dir
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            zf.extractall(dest_dir)
        return dest_dir
    if copy:
        target = os.path.join(dest_dir, os.path.basename(path))
        if os.path.abspath(target) != os.path.abspath(path):
            shutil.copy2(path, target)
        return target
    return path


def _from_huggingface(repo_id: str, dest_dir: str) -> str:
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:
        raise RuntimeError(
            "hf:// uris need huggingface_hub (bundled with transformers); "
            f"import failed: {e}") from e
    return snapshot_download(repo_id=repo_id, local_dir=dest_dir)
