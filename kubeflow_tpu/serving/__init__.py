"""Serving layer — KServe-equivalent model serving (SURVEY.md §2.4)."""

from kubeflow_tpu.serving.controller import (
    Autoscaler, CanaryGate, RuntimeRegistry, ServingController,
    ServingTicker,
)
from kubeflow_tpu.serving.jax_model import (
    JAXModel, LLMModel, enable_compile_cache,
)
from kubeflow_tpu.serving.llm import GenRequest, LLMEngine, SamplingParams
from kubeflow_tpu.serving.model import (
    Model, ModelMissing, ModelNotReady, ModelRepository,
)
from kubeflow_tpu.serving.protocol import (
    InferRequest, InferResponse, InferTensor,
)
from kubeflow_tpu.serving.agents import BatchingModel, LoggingModel, ModelPuller
from kubeflow_tpu.serving.paged_kv import RadixPrefixCache
from kubeflow_tpu.serving.router import (
    FleetRouter, GraphRouter, HashRing, TrafficSplitter, radix_block_key,
)
from kubeflow_tpu.serving.scheduler import SchedulerConfig, StepScheduler
from kubeflow_tpu.serving.server import InferenceClient, ModelServer
from kubeflow_tpu.serving.v2_socket import V2SocketClient, V2SocketServer
from kubeflow_tpu.serving.storage import download
from kubeflow_tpu.serving.types import (
    CanarySLO, ComponentSpec, GraphNode, GraphNodeType, GraphStep,
    InferenceGraph, InferenceService, ModelFormat, PredictorSpec,
    ServingRuntime, TrainedModel,
)

__all__ = [
    "Autoscaler", "BatchingModel", "CanaryGate", "CanarySLO",
    "ComponentSpec", "FleetRouter", "GenRequest", "GraphNode",
    "GraphNodeType", "HashRing", "LoggingModel", "ModelPuller",
    "GraphRouter", "GraphStep", "InferRequest", "InferResponse",
    "InferTensor", "InferenceClient", "InferenceGraph", "InferenceService",
    "JAXModel", "LLMEngine", "LLMModel", "Model", "ModelFormat",
    "ModelMissing", "ModelNotReady", "ModelRepository", "ModelServer",
    "PredictorSpec", "RadixPrefixCache", "RuntimeRegistry", "SamplingParams",
    "SchedulerConfig", "ServingController", "ServingRuntime", "ServingTicker",
    "StepScheduler", "TrafficSplitter", "TrainedModel", "V2SocketClient",
    "V2SocketServer", "download", "enable_compile_cache", "radix_block_key",
]
