"""LLM serving engine — continuous batching over the Llama decode path.

This is the TPU-native answer to the reference's huggingfaceserver/vLLM
runtime (SURVEY.md §2.4 'Runtime servers': LLM generate endpoints): a
slot-based continuous-batching engine where

- the KV cache is ONE static-shape arena [layers, max_batch, max_seq, ...]
  (XLA-friendly: no dynamic shapes, ever);
- prompts prefill into padded length buckets (few compile variants), and
  their KV rows are inserted into free slots with dynamic_update_slice;
- every step runs ONE jitted decode+sample over all slots — requests join
  and leave between steps without recompiling (the continuous-batching
  property that keeps the MXU fed at high request churn);
- sampling (greedy/temperature/top-k/top-p) runs on-device in the same
  program, so only sampled token ids cross back to the host.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models import llama
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.obs.histogram import Histogram, log_buckets

# Request-latency buckets at factor 2**0.25 (~19% relative error) instead
# of the default factor-2: serving A/B comparisons (canary gate, the
# co-located-vs-disagg bench legs) discriminate distributions well inside
# one octave of each other, which factor-2 buckets collapse into a tie.
_REQ_LAT_BUCKETS = log_buckets(0.001, 64.0, factor=2 ** 0.25)
from kubeflow_tpu.serving.scheduler import (
    QuantConfig, SchedulerConfig, StepScheduler, ceil_pow2,
)

logger = logging.getLogger(__name__)

# kernel-downgrade reasons already logged this process: the event is
# counted per engine (kft_model_kernel_downgrades_total) but LOGGED once —
# a fleet restarting 128 replicas must not print 128 identical warnings
_downgrades_logged: set = set()


def _log_downgrade_once(requested: str, reason: str) -> None:
    if reason in _downgrades_logged:
        return
    _downgrades_logged.add(reason)
    logger.warning(
        "decode kernel %r downgraded to 'gather' (%s): losing the "
        "block-resident fast path's bandwidth advantage", requested, reason)


def _log_quant_downgrade_once(requested: str, reason: str) -> None:
    """Quant downgrades share the once-per-process set with kernel
    downgrades: the fleet case is identical (128 replicas, one warning),
    but the message must say WHICH dtype the engine is actually serving
    at — a quant fallback is never a silent dtype change."""
    if reason in _downgrades_logged:
        return
    _downgrades_logged.add(reason)
    logger.warning(
        "quant mode %s downgraded to unquantized (%s): serving at full "
        "bytes-per-weight / bytes-per-KV-token", requested, reason)


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0          # 0 = greedy
    top_k: int = 0                    # 0 = off
    top_p: float = 1.0                # 1 = off
    eos_id: Optional[int] = None
    # any of these ends generation like eos (finish_reason "stop"); text
    # stop STRINGS live a layer up in LLMModel, which owns the tokenizer
    stop_token_ids: tuple = ()


@dataclasses.dataclass
class GenRequest:
    id: int
    prompt: list[int]
    sampling: SamplingParams
    generated: list[int] = dataclasses.field(default_factory=list)
    # per-generated-token logprob under the model distribution
    logprobs: list[float] = dataclasses.field(default_factory=list)
    done: bool = False
    aborted: bool = False
    # set by a text-level stop-string watcher before aborting: the abort
    # then reads as a clean "stop" finish, not a client disconnect
    stop_matched: bool = False
    slot: Optional[int] = None
    # disaggregated prefill tier (serving/disagg.py): park the request
    # after prefill + first token instead of decoding — KV stays resident
    # (blocks refcount-pinned) until export_held_kv/release_held
    hold_after_prefill: bool = False
    # observability: the request's trace context ((trace_id, span_id) of
    # its queue span — decode/prefill spans attribute to it), wall-clock
    # latency marks (enqueue/first-token/last-commit/done) feeding the
    # kft_model_request_{ttft,itl,e2e}_seconds histograms, and the live
    # span handles the engine closes as the request advances
    trace: Optional[tuple] = None
    t_enqueue: float = 0.0
    t_first_token: float = 0.0
    # first DECODE commit (token #2) — on a disagg decode pod this bounds
    # the migration decomposition: prefill-complete -> first decode commit
    t_second_token: float = 0.0
    t_last_commit: float = 0.0
    t_done: float = 0.0
    spans: dict = dataclasses.field(default_factory=dict)

    @property
    def finish_reason(self) -> str:
        if self.stop_matched:
            # a stop-string match is a clean stop even when the request
            # also hit its length cap before the watcher saw the match
            return "stop"
        if self.aborted:
            return "abort"
        if self.generated and (
                (self.sampling.eos_id is not None
                 and self.generated[-1] == self.sampling.eos_id)
                or self.generated[-1] in self.sampling.stop_token_ids):
            return "stop"
        return "length"


@dataclasses.dataclass
class _ChunkedPrefill:
    """A long prompt streaming through chunked prefill across engine
    steps (the scheduler interleaves one chunk per step with decode).
    ``offset`` is the next position to prefill; positions < ``share_len``
    are radix-shared (their chunks are skipped for compute and their
    writes masked to scratch); ``tables`` is the device snapshot of the
    block tables taken at reservation (this slot's row is immutable)."""

    req: GenRequest
    offset: int
    share_len: int
    tables: Any
    x_last: Any = None


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket {buckets[-1]}")


def greedy_argmax(logits):
    """Greedy pick with an EXPLICIT stable lowest-index tie-break.

    Exact logit ties are routine in bf16 (activations quantize to 8
    mantissa bits), and ``jnp.argmax``'s tie winner is formally
    first-index but travels through backend-specific reduction trees.
    This construction — min index among maximizers — is deterministic by
    value comparison alone, so every path that greedy-decodes (decode
    sampler, first-token sampler, speculative verify) breaks ties the
    same way on the same values. Works on any [..., V] logits."""
    vocab = logits.shape[-1]
    is_max = logits == jnp.max(logits, axis=-1, keepdims=True)
    idx = jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                   logits.ndim - 1)
    return jnp.min(jnp.where(is_max, idx, vocab), axis=-1).astype(jnp.int32)


def sample_logits(logits, rng, temperature, top_k, top_p,
                  greedy_only: bool = False):
    """On-device sampling: greedy when temperature==0, else
    temperature/top-k/top-p. temperature/top_k/top_p are per-batch arrays
    ([B]); top_k==0 / top_p==1 disable the respective filter.

    ``greedy_only`` (STATIC) skips the full-vocab sort entirely — the
    sort is O(V log V) bitonic passes on TPU and dominates the decode
    step for greedy batches, which are the common serving case."""
    vocab = logits.shape[-1]
    greedy = greedy_argmax(logits)
    if greedy_only:
        return greedy.astype(jnp.int32)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    sorted_asc = jnp.sort(scaled, axis=-1)               # [B, V] ascending
    # top-k: kth-largest value per row; rows with top_k==0 keep everything
    k_idx = jnp.clip(vocab - top_k, 0, vocab - 1)
    kth = jnp.take_along_axis(sorted_asc, k_idx[:, None], axis=-1)
    kth = jnp.where((top_k > 0)[:, None], kth, -jnp.inf)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    # top-p (nucleus) over the top-k-MASKED distribution (vLLM/HF ordering:
    # k first, then p renormalized on the survivors). The mask is a monotone
    # value threshold, so the sorted masked array comes from the existing
    # sort — no second O(V log V) sort in the decode hot loop.
    sorted_desc = jnp.where(sorted_asc < kth, -jnp.inf, sorted_asc)[:, ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.minimum(
        jnp.sum(cum < top_p[:, None], axis=-1), vocab - 1)
    cutoff = jnp.take_along_axis(sorted_desc, cutoff_idx[:, None], axis=-1)
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


class LLMEngine:
    """Continuous-batching generation over llama prefill/decode_step."""

    def __init__(self, params, cfg: llama.LlamaConfig, *,
                 max_batch: int = 8, max_seq: int = 1024,
                 prefill_buckets: Sequence[int] = (64, 128, 256, 512),
                 kv_block_size: Optional[int] = None,
                 kv_num_blocks: Optional[int] = None,
                 decode_chunk: int = 8,
                 decode_pipeline: bool = True,
                 kernel: str = "auto",
                 mesh=None,
                 scheduler: Optional[SchedulerConfig] = None,
                 quant: Optional[QuantConfig] = None,
                 obs: Optional[obs_trace.SpanCollector] = None):
        from kubeflow_tpu.serving.paged_kv import (
            PagedKV, _lm_head as lm_head_fn, paged_prefill_chunk
            as paged_prefill_chunk_fn, paged_verify_step
            as paged_verify_step_fn, resolve_decode_kernel,
        )
        from kubeflow_tpu.serving.quant import (
            is_weight_quantized, quantize_weights, resolve_quant,
        )

        self.cfg = cfg
        self.mesh = mesh
        # decode-attention path (paged_kv module docstring): the
        # block-resident Pallas kernel is the TPU default — including
        # under a mesh, where it runs shard_map'd over the heads/KV
        # tensor axis (ops/pallas_paged_attention). Resolution is
        # delegated to paged_kv so self.kernel always names the path the
        # decode step actually executes; a downgrade the caller did not
        # ask for (gpu, or an unshardable mesh topology) is COUNTED
        # (kft_model_kernel_downgrades_total) and logged once instead of
        # silently losing ~3.7x decode bandwidth.
        resolved, downgrade = resolve_decode_kernel(
            kernel, mesh=mesh, n_kv_heads=cfg.n_kv_heads)
        self.kernel = resolved
        self.kernel_downgrades = 0
        if downgrade is not None:
            self.kernel_downgrades = 1
            _log_downgrade_once(kernel, downgrade)
        # quantized serving (serving/quant.py): resolve the requested
        # config against the platform/model. A mode the platform can't
        # honor (no fp8 dtype) or the model can't (MoE expert weights)
        # falls back to unquantized — counted on the SAME downgrade
        # surface as kernel downgrades (kft_model_kernel_downgrades_total
        # plus its own quant_downgrades), logged once per process, never
        # a silent dtype change. The explicit quant= argument wins over
        # the scheduler policy's copy (one resolution authority).
        if quant is None and scheduler is not None:
            quant = scheduler.quant
        self.quant_requested = quant
        self.quant, quant_downgrades = resolve_quant(quant, cfg=cfg)
        self.quant_downgrades = len(quant_downgrades)
        self.kernel_downgrades += self.quant_downgrades
        for q_requested, q_reason in quant_downgrades:
            _log_quant_downgrade_once(q_requested, q_reason)
        if (self.quant.weight_dtype == "int8"
                and not is_weight_quantized(params)):
            # quantize ONCE at engine build (the LLMModel.load() path):
            # per-output-channel scales; decode, chunked prefill, bucket
            # prefill and spec verify all read the same int8 tree
            params = quantize_weights(params, cfg)
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.buckets = sorted(b for b in prefill_buckets if b <= max_seq)
        if not self.buckets:
            raise ValueError("no prefill bucket fits max_seq")
        # block-paged KV: pool memory = kv_num_blocks * kv_block_size tokens
        # (default: the dense arena's capacity + the scratch block); shrink
        # kv_num_blocks to serve more concurrent requests per byte.
        # Block size must divide max_seq and every bucket (prefill writes
        # whole blocks); the default picks the largest power of 2 <= 64
        # that does.
        if kv_block_size is None:
            kv_block_size = 1
            while (kv_block_size < 64
                   and max_seq % (kv_block_size * 2) == 0
                   and all(b % (kv_block_size * 2) == 0
                           for b in self.buckets)):
                kv_block_size *= 2
        for b in self.buckets + [max_seq]:
            if b % kv_block_size:
                raise ValueError(
                    f"kv_block_size={kv_block_size} must divide max_seq and "
                    f"every prefill bucket (got {b})")
        if kv_num_blocks is None:
            kv_num_blocks = max_batch * (max_seq // kv_block_size) + 1
        kv_sh = len_sh = sc_sh = None
        if mesh is not None:
            # tensor-parallel serving: the KV pool shards over the mesh's
            # `tensor` axis on the kv-head dim (matching the TP-sharded
            # params the loader placed); everything else is replicated and
            # jit auto-partitions the prefill/decode programs (SPMD — XLA
            # inserts the collectives). Host-side tables stay numpy. The
            # pool allocates directly with this sharding — a pod-sized
            # pool must never transit one chip unsharded.
            from jax.sharding import NamedSharding, PartitionSpec

            tp = mesh.shape.get("tensor", 1)
            if cfg.n_kv_heads % tp:
                raise ValueError(
                    f"n_kv_heads={cfg.n_kv_heads} not divisible by "
                    f"tensor={tp}")
            kv_sh = NamedSharding(
                mesh, PartitionSpec(None, None, None, "tensor", None))
            len_sh = NamedSharding(mesh, PartitionSpec())
            # quantized pools: the [L, NB, KV] scale tables shard on the
            # kv-head dim with the pool (same divisibility, checked above)
            sc_sh = NamedSharding(mesh, PartitionSpec(None, None, "tensor"))
        self.paged = PagedKV(cfg=cfg, max_batch=max_batch, max_seq=max_seq,
                             block_size=kv_block_size,
                             num_blocks=kv_num_blocks,
                             kv_sharding=kv_sh, len_sharding=len_sh,
                             quant_kv=self.quant.kv_dtype,
                             scale_sharding=sc_sh)
        self.cache = self.paged.cache
        self._free: list[int] = list(range(max_batch))
        self._active: dict[int, GenRequest] = {}     # slot -> request
        self._waiting: list[GenRequest] = []
        self._aborted: set[int] = set()              # request ids to retire
        # disaggregated prefill tier: slot -> request parked after prefill
        # (hold_after_prefill) awaiting KV export/migration; their blocks
        # stay refcount-pinned so eviction can never reach them
        self._held: dict[int, GenRequest] = {}
        # control ops (export/inject/release from disagg glue threads):
        # the decode dispatch donates the cache buffers, so ALL cache
        # mutation must run on the step thread — ops queue here and drain
        # at the top of step()
        self._ctl: list = []
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._tokens = np.zeros((max_batch,), np.int32)   # next input token
        self._rng = jax.random.key(0)
        self.steps = 0
        self.generated_tokens = 0
        self.prefill_dispatches = 0       # observability: admission batching
        # multi-step decode: one dispatch runs `decode_chunk` decode+sample
        # steps under lax.scan, amortizing host->device dispatch latency
        # (vLLM multistep role). Requests finishing mid-chunk are trimmed on
        # the host; their overshoot tokens land in their own reserved blocks
        # or the scratch block, never another request's.
        self.decode_chunk = max(1, int(decode_chunk))
        # double-buffered decode: dispatch chunk N+1 BEFORE fetching chunk
        # N's tokens, so device compute overlaps host transfer+bookkeeping
        # (critical on a remote-tunnel chip where each fetch pays an RTT).
        # The next chunk's input token is the DEVICE-side scan carry; host
        # token writes (fresh admissions) override it through a jitted
        # merge, so the dispatch never waits on a host read-back.
        self.decode_pipeline = bool(decode_pipeline)
        self._inflight: Optional[dict] = None
        self._fresh = np.ones((max_batch,), bool)   # host token overrides
        # step scheduler (serving/scheduler.py): per-step prefill token
        # quota, interleaved chunked prefill, adaptive decode-chunk trims,
        # and the counter set /metrics exports
        self.sched = StepScheduler(scheduler, default_budget=self.buckets[-1],
                                   decode_chunk=self.decode_chunk)
        # observability (obs/): every request yields a queue span,
        # per-prefill-chunk spans and per-decode-dispatch spans into the
        # process collector, plus the three request-latency histograms
        # /metrics serves as kft_model_request_{ttft,itl,e2e}_seconds
        self.obs = obs or obs_trace.collector()
        self.request_hists = {"ttft": Histogram(_REQ_LAT_BUCKETS),
                              "itl": Histogram(_REQ_LAT_BUCKETS),
                              "e2e": Histogram(_REQ_LAT_BUCKETS)}
        self.paged.prefix_cache = self.sched.cfg.radix_cache
        # in-flight chunked prefills, slot -> state (insertion order = FIFO)
        self._chunked: dict[int, _ChunkedPrefill] = {}
        # chunk width is STATIC (one compile): the largest bucket, capped
        # by the quota so one chunk always fits one step's budget
        self._chunk_width = max(1, min(self.buckets[-1],
                                       self.sched.prefill_budget()))
        # speculative decoding (scheduler knob): host-side drafter +
        # batched verify step. The drafter proposes per-stream token
        # continuations; one _verify dispatch scores all of them and the
        # accepted prefix commits — greedy outputs token-identical to
        # the non-speculative path, >=1 token per verify always.
        self.spec = None
        if self.sched.cfg.spec_decode:
            from kubeflow_tpu.serving.spec_decode import make_drafter

            self.spec = make_drafter(self.sched.cfg.spec_drafter,
                                     self.sched.cfg.spec_k)

        self._prefill = jax.jit(
            lambda p, toks, lens, cache: llama.prefill(
                p, toks, cfg, cache, lengths=lens))
        # chunked prefill for prompts longer than every bucket: fixed
        # chunk size (the largest bucket) + traced offset/length keep the
        # compile count O(1) in prompt length
        self._prefill_chunk = jax.jit(
            lambda p, toks, cache, tables, slot, offset, length, share:
                paged_prefill_chunk_fn(
                    p, toks, self.cfg, cache, tables, slot, offset, length,
                    share),
            donate_argnums=(2,))
        # the lm head runs ONCE on the final chunk's hidden row, not per
        # chunk (full-vocab matmul is the expensive part of short chunks)
        self._chunk_lm_head = jax.jit(
            lambda p, x_last: lm_head_fn(p, x_last, self.cfg))
        # first-token sampling + its logprob in ONE jitted call: computing
        # log_softmax eagerly per admitted request costs an op-by-op
        # full-vocab dispatch + transfer (catastrophic on a remote chip)
        self._first_sample = jax.jit(
            lambda logits, rng, t, k, p: (
                (tok := sample_logits(logits, rng, t, k, p)),
                jnp.take_along_axis(logits, tok[:, None], axis=-1)[:, 0]
                - jax.nn.logsumexp(logits, axis=-1)))
        self._decode = jax.jit(
            self._decode_impl, donate_argnums=(2,),
            static_argnames=("greedy_only", "kernel", "chunk_len"))
        # AOT-compiled steady-state decode program, installed by
        # precompile(): the executable-depot fast path for serving
        # replicas — a fleet scale-up deserializes the program the first
        # replica published instead of compiling it cold. Dispatches whose
        # static config differs (non-greedy batch, adaptive chunk trim)
        # fall back to the jitted path above.
        self._compiled_decode = None
        # prefill-tier twin (precompile(tier="prefill")): the AOT chunked-
        # prefill program — the prefill pod's steady-state program under
        # its own depot key scope
        self._compiled_prefill_chunk = None
        self.depot_outcome: Optional[str] = None
        # speculative verify: greedy target chain + chosen-token logprobs
        # for a [B, S] candidate batch in ONE dispatch. S is pow2-padded
        # by the caller, so the compile count is log2(spec_k+1) — the
        # same static-width scheme the adaptive decode chunk uses.
        def _verify_impl(p, toks, cache, tables, limit):
            logits, cache = paged_verify_step_fn(
                p, toks, self.cfg, cache, tables, limit)
            # the SAME stable tie-break the decode sampler uses: the
            # token-identity guarantee rests on both paths picking the
            # same greedy token from the same logit values
            nxt = greedy_argmax(logits)
            lp = jnp.take_along_axis(
                logits, nxt[..., None], axis=-1)[..., 0] \
                - jax.nn.logsumexp(logits, axis=-1)
            return nxt, lp, cache

        self._verify = jax.jit(_verify_impl, donate_argnums=(2,))
        self._set_lens = jax.jit(
            lambda cache, lens: {**cache, "len": lens},
            donate_argnums=(0,))
        self._merge_tok = jax.jit(
            lambda carry, upd, mask: jnp.where(mask, upd, carry))
        self._insert_batch = jax.jit(self._insert_batch_impl,
                                     donate_argnums=(0,))
        self._set_len = jax.jit(
            lambda cache, length, slot: {
                **cache, "len": cache["len"].at[slot].set(length)},
            donate_argnums=(0,))

    # ---------------- jitted bodies ----------------

    def _decode_impl(self, params, token, cache, tables, active, temperature,
                     top_k, top_p, rng, greedy_only=False, kernel="gather",
                     chunk_len=1):
        from kubeflow_tpu.serving.paged_kv import paged_decode_step

        def one_step(carry, rng_step):
            token, cache = carry
            logits, cache = paged_decode_step(
                params, token, self.cfg, cache, tables, kernel=kernel,
                mesh=self.mesh)
            nxt = sample_logits(logits, rng_step, temperature, top_k,
                                top_p, greedy_only=greedy_only)
            # chosen-token logprob under the MODEL distribution (OpenAI
            # convention: pre-temperature/filtering). Gather-then-logsumexp
            # rather than materializing the full [B, V] log_softmax.
            lp = jnp.take_along_axis(
                logits, nxt[:, None], axis=-1)[:, 0] \
                - jax.nn.logsumexp(logits, axis=-1)
            # idle slots: pin len to 0 so the cursor can't creep toward
            # max_seq (their scatter lands in the scratch block 0)
            cache["len"] = jnp.where(active, cache["len"], 0)
            return (nxt, cache), (nxt, lp)

        rngs = jax.random.split(rng, chunk_len)
        (next_tok, cache), (toks, lps) = jax.lax.scan(
            one_step, (token, cache), rngs)
        # next_tok: the device-side carry the pipelined dispatch feeds the
        # NEXT chunk without waiting for the host to read toks back
        return toks, lps, next_tok, cache        # toks/lps: [chunk, B]

    def _insert_batch_impl(self, cache, k_new, v_new, blk_ids, lengths,
                           slots):
        from kubeflow_tpu.serving.paged_kv import paged_insert_batch

        return paged_insert_batch(cache, k_new, v_new, blk_ids, lengths,
                                  slots)

    # ---------------- public API ----------------

    def precompile(self, depot=None, stats=None, wait_s: float = 0.0,
                   tier: str = "") -> str:
        """Split the decode compile from request #1 (the serving analogue
        of ``Trainer.precompile``): AOT-lower the steady-state decode
        program — full ``decode_chunk``, greedy batch, the engine's
        resolved kernel; the dominant program of the shared-system-prompt
        serving workload — and compile it NOW, fetching the executable
        from an executable depot (``parallel/depot.py``) when one is
        given and publishing on a miss. A fleet scale-up replica whose
        warm-pool claim pre-fetched the entry therefore deserializes in
        place of the cold compile; every degraded path stays a counted
        local compile (depot fallback semantics), never a failure.
        Returns the depot outcome ("hit" / "published" / "compiled" /
        "no_depot"), also kept as ``self.depot_outcome``. Other compile
        variants (non-greedy batches, adaptive chunk trims, prefill
        widths) still compile lazily via the jitted path — the
        persistent XLA compile cache covers those across replicas."""
        from kubeflow_tpu.parallel.depot import load_or_compile

        b = self.max_batch
        if tier == "prefill":
            # the prefill tier's steady-state program is the CHUNKED
            # prefill (long prompts stream through it; bucketed admission
            # stays lazily jitted) — keyed under its own stage scope, the
            # PR 11 per-stage scheme reused for the two tier programs of
            # one model: a scale-up prefill replica hits THIS entry and a
            # decode replica hits the decode entry, never each other's
            lowered = self._prefill_chunk.lower(
                self.params, jnp.zeros((1, self._chunk_width), jnp.int32),
                self.cache,
                jnp.zeros((b, self.paged.max_blocks_per_seq), jnp.int32),
                jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0))
            self._compiled_prefill_chunk, outcome = load_or_compile(
                lowered, depot, mesh=self.mesh, stats=stats, wait_s=wait_s,
                stage="serving-prefill",
                extra=(f"chunk={self._chunk_width}", self.quant.tag()))
            self.depot_outcome = outcome
            return outcome
        lowered = self._decode.lower(
            self.params, jnp.zeros((b,), jnp.int32), self.cache,
            jnp.zeros((b, self.paged.max_blocks_per_seq), jnp.int32),
            jnp.zeros((b,), bool), jnp.zeros((b,), jnp.float32),
            jnp.zeros((b,), jnp.int32), jnp.ones((b,), jnp.float32),
            jax.random.key(0), greedy_only=True, kernel=self.kernel,
            chunk_len=self.decode_chunk)
        # the quant tag ALWAYS joins the fingerprint ("quant=off" when
        # unquantized): same-HLO entries under different quant configs
        # can never collide, and a warm claim's key-agnostic prefetch
        # therefore lands the per-config executable automatically
        self._compiled_decode, outcome = load_or_compile(
            lowered, depot, mesh=self.mesh, stats=stats, wait_s=wait_s,
            stage=("serving-decode-tier" if tier == "decode" else None),
            extra=("serving-decode", self.quant.tag()))
        self.depot_outcome = outcome
        return outcome

    def validate_prompt(self, prompt: Sequence[int],
                        sampling: Optional[SamplingParams] = None) -> None:
        """Raise if the prompt can't be served. Called by add_request; also
        callable up front to vet a whole batch before enqueuing any of it."""
        from kubeflow_tpu.serving.paged_kv import blocks_for

        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + 1 > self.max_seq:
            # prompts beyond the largest bucket stream through CHUNKED
            # prefill (paged_prefill_chunk); max_seq is the only cap
            raise ValueError(f"prompt too long for max_seq={self.max_seq}")
        if sampling is not None:
            # a reservation that can NEVER succeed must fail fast here —
            # re-queueing it would spin generate()'s drain loop forever
            need = min(
                blocks_for(len(prompt) + sampling.max_tokens,
                           self.paged.block_size),
                self.paged.max_blocks_per_seq)
            usable = self.paged.num_blocks - 1       # block 0 is scratch
            if need > usable:
                raise ValueError(
                    f"request needs {need} KV blocks but the pool only has "
                    f"{usable}; raise kv_num_blocks or lower max_tokens")

    def add_request(self, prompt: Sequence[int],
                    sampling: Optional[SamplingParams] = None,
                    trace: Optional[str] = None,
                    hold_after_prefill: bool = False) -> GenRequest:
        """``trace``: an incoming W3C traceparent (router/server span) —
        the request's queue span roots under it, so the full
        router -> server -> queue -> prefill -> decode chain shares one
        trace id across processes. ``hold_after_prefill``: disaggregated
        prefill tier — park after prefill + first token for KV export
        instead of decoding."""
        sampling = sampling or SamplingParams()
        self.validate_prompt(prompt, sampling)
        req = GenRequest(id=next(self._ids), prompt=list(map(int, prompt)),
                         sampling=sampling,
                         hold_after_prefill=bool(hold_after_prefill))
        req.t_enqueue = time.time()
        qspan = self.obs.start(
            "request.queue", parent=trace,
            attrs={"request_id": req.id, "prompt_tokens": len(req.prompt)})
        req.spans["queue"] = qspan
        req.trace = (qspan.trace_id, qspan.span_id)
        with self._lock:
            self._waiting.append(req)
        return req

    def abort(self, reqs: Sequence[GenRequest]) -> None:
        """Give up on requests (caller timeout / disconnect): waiting ones
        leave the queue immediately; active ones release their slot at the
        start of the next step. Without this, a timed-out caller's slots
        would stay occupied until max_tokens (ADVICE r1 finding c)."""
        ids = set()
        for r in reqs:
            r.aborted = True
            r.done = True
            ids.add(r.id)
            # still-open spans (a queue span of a never-admitted request)
            # close NOW with the abort attr — an aborted request must
            # leave a coherent trace, never a dangling open span
            for sp in r.spans.values():
                if sp.t1 is None:
                    self.obs.end(sp, aborted=True)
        with self._lock:
            self._waiting = [r for r in self._waiting if r.id not in ids]
            self._aborted.update(ids)

    # ------------- disaggregated prefill/decode (serving/disagg.py) -------
    # Engine-thread-only: these mutate the cache (whose buffers the decode
    # dispatch donates), so cross-thread callers MUST route through
    # submit_ctl. Single-threaded tests may call them directly between
    # step()s.

    def held_requests(self) -> list[GenRequest]:
        return list(self._held.values())

    def export_held_kv(self, req: GenRequest) -> Optional[dict]:
        """Package a held request's PROMPT blocks for migration: gather
        the first ``blocks_for(len(prompt))`` blocks of its reservation
        (the empty generation-budget tail never travels) to host numpy,
        plus everything the decode tier needs to resume — prompt, the
        prefill-sampled token #1 and its logprob, sampling params and the
        original enqueue time (so the decode pod's latency marks stay on
        the request's true clock). Returns None when the request was
        aborted/released before export (the caller drops the migration)."""
        from kubeflow_tpu.serving.paged_kv import (
            blocks_for, gather_kv_blocks,
        )

        slot = req.slot
        if slot is None or self._held.get(slot) is not req:
            return None
        bs = self.paged.block_size
        n = blocks_for(len(req.prompt), bs)
        ids = self.paged.slot_blocks(slot)[:n]
        return {
            "prompt": list(req.prompt),
            "first_token": int(req.generated[0]),
            "first_lp": float(req.logprobs[0]),
            "sampling": dataclasses.asdict(req.sampling),
            "t_enqueue": req.t_enqueue,
            "t_prefill_done": req.t_first_token,
            "block_size": bs,
            "n_blocks": n,
            "blocks": gather_kv_blocks(self.cache, ids),
        }

    def release_held(self, req: GenRequest) -> bool:
        """Drop a held request's slot + block reservation — the prefill
        side of the ownership edge, called after the decode tier acked
        the handoff (ownership moved) OR on a failed/aborted migration
        (ownership stays dropped; radix-published blocks remain cached
        and evictable, so a local re-prefill is one cheap chunk)."""
        slot = req.slot
        if slot is None or self._held.get(slot) is not req:
            return False
        del self._held[slot]
        req.done = True
        self.paged.release(slot)
        self._free.append(slot)
        return True

    def inject_request(self, prompt: Sequence[int],
                       sampling: SamplingParams, *, first_token: int,
                       first_lp: float, blocks: dict, n_blocks: int,
                       t_enqueue: float = 0.0) -> Optional[GenRequest]:
        """Decode-tier admission of a migrated prefill: reserve a slot,
        scatter the imported prompt blocks into the pool (radix-shared
        prefix blocks are skipped — the pool already holds them), set the
        slot length and commit token #1 exactly like a local admission.
        The reservation refcounts every imported block BEFORE the scatter,
        so concurrent eviction pressure can never reclaim a mid-handoff
        block. Returns None when no slot or pool capacity is available
        (the caller nacks the handoff and the prefill pod falls back to
        local re-prefill)."""
        from kubeflow_tpu.serving.paged_kv import scatter_kv_blocks

        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
        L = len(prompt)
        n_shared = self.paged.reserve(
            slot, L, sampling.max_tokens, min_blocks=n_blocks,
            prompt=prompt, defer_publish=True)
        if n_shared is None:
            with self._lock:
                self._free.append(slot)
            return None
        req = GenRequest(id=next(self._ids),
                         prompt=list(map(int, prompt)), sampling=sampling)
        req.t_enqueue = t_enqueue or time.time()
        ids = self.paged.slot_blocks(slot)[:n_blocks]
        if n_shared < n_blocks:
            sub = {k: v[:, n_shared:n_blocks]
                   for k, v in blocks.items()}
            self.cache = scatter_kv_blocks(self.cache, ids[n_shared:], sub)
        self.cache = self._set_len(self.cache, jnp.int32(L),
                                   jnp.int32(slot))
        # publish the imported full prompt blocks to THIS pool's radix
        # tree: a later fully-shared-prefix request can then bypass the
        # prefill tier entirely and admit here at radix-hit cost
        self.paged.publish_prompt_blocks(slot, prompt, L)
        self._post_admit(req, slot, int(first_token), float(first_lp))
        return req

    # ---------------- observability hooks ----------------

    def _end_queue_span(self, req: GenRequest, slot: int,
                        n_shared: int) -> None:
        """The queue span ends at slot assignment (admission), not at
        first token — TTFT minus queue time is the prefill cost."""
        sp = req.spans.get("queue")
        if sp is not None and sp.t1 is None:
            self.obs.end(sp, slot=slot, shared_blocks=n_shared)

    def _dispatch_span(self, name: str, reqs: Sequence[GenRequest],
                       **attrs) -> Any:
        """Engine-level span (decode/verify/batched-prefill dispatch):
        owned by ONE trace when every covered request shares it, else
        top-level with the participating ids in ``attrs.trace_ids`` so
        per-trace filtering still finds it."""
        tids = sorted({r.trace[0] for r in reqs if r.trace})
        kw: dict = {}
        if len(tids) == 1:
            kw["trace_id"] = tids[0]
            if len(reqs) == 1 and reqs[0].spans.get("queue") is not None:
                kw["parent"] = reqs[0].spans["queue"]
        elif tids:
            attrs["trace_ids"] = tids
        return self.obs.start(name, attrs=attrs, **kw)

    def _note_request_latency(self, req: GenRequest, n_new: int) -> None:
        """Feed the request histograms after committing ``n_new`` tokens
        in one read-back. The first token closes TTFT; later commits
        spread the read-back gap evenly over the chunk's tokens (the
        honest per-token latency of multistep decode — tokens inside one
        dispatch arrive together, so per-commit wall deltas would read
        as zero)."""
        if n_new <= 0:
            return
        now = time.time()
        if req.t_first_token == 0.0:
            req.t_first_token = now
            if req.t_enqueue:
                self.request_hists["ttft"].observe(now - req.t_enqueue)
            n_new -= 1
        elif req.t_second_token == 0.0:
            # first commit past token #1 = the first DECODE commit; on a
            # disagg decode pod this closes the migration decomposition
            req.t_second_token = now
        if n_new > 0 and req.t_last_commit:
            gap = max(0.0, now - req.t_last_commit) / n_new
            itl = self.request_hists["itl"]
            for _ in range(n_new):
                itl.observe(gap)
        req.t_last_commit = now

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._waiting or self._active or self._chunked
                        or self._ctl)

    def submit_ctl(self, fn) -> None:
        """Queue ``fn`` to run on the step thread at the top of the next
        step() — the only safe way for another thread to touch engine/
        cache state (the decode dispatch donates the cache buffers).
        Callers needing the result wrap ``fn`` to capture it and wake the
        model loop (serving/disagg.py TierRuntime.run_on_engine)."""
        with self._lock:
            self._ctl.append(fn)

    def _drain_ctl(self) -> None:
        with self._lock:
            ops, self._ctl = self._ctl, []
        for fn in ops:
            fn()

    def scheduler_stats(self) -> dict:
        """Scheduler counters + gauges for /metrics (occupancy, queue
        depth, token backlog, prefix-hit and preempt counters — the
        serving controller's autoscale/affinity signals)."""
        with self._lock:
            waiting = len(self._waiting)
            # token backlog: prompt + generation budget of queued requests
            # plus the un-prefilled remainder of in-flight chunked prompts
            # — the work this replica owes but has not scheduled, the
            # scale-up signal queue_depth alone understates for long
            # prompts
            backlog = sum(len(r.prompt) + r.sampling.max_tokens
                          for r in self._waiting)
        # the step loop mutates _chunked WITHOUT the lock: snapshot the
        # values in one C-level call (GIL-atomic) before iterating, or a
        # mid-scrape chunk completion raises dict-changed-size and the
        # busiest replica goes invisible to the autoscaler
        backlog += sum(max(0, len(st.req.prompt) - st.offset)
                       for st in list(self._chunked.values()))
        return self.sched.snapshot(
            active=len(self._active), waiting=waiting,
            chunked=len(self._chunked), max_batch=self.max_batch,
            prefix_hits=self.paged.prefix_hits,
            prefix_queries=self.paged.prefix_queries,
            backlog_tokens=backlog)

    def step(self) -> list[GenRequest]:
        """Admit waiting requests, dispatch one decode chunk, retire
        finished. Pipelined (default): the dispatch goes out BEFORE the
        previous chunk's tokens are fetched, so device compute overlaps
        host transfer + bookkeeping; results therefore lag one chunk.
        Returns requests that finished this step."""
        self.sched.note_step()
        self._drain_ctl()
        with self._lock:
            aborted, self._aborted = self._aborted, set()
        if aborted:
            for slot, req in list(self._active.items()):
                if req.id in aborted:
                    del self._active[slot]
                    self.paged.release(slot)
                    self._free.append(slot)
            # a held prefill whose request aborted mid-migration releases
            # its side HERE — the prefill half of the "releases on both
            # sides" contract (the decode half is disagg release/collect)
            for slot, req in list(self._held.items()):
                if req.id in aborted:
                    del self._held[slot]
                    self.paged.release(slot)
                    self._free.append(slot)
            # abort of a request whose chunked prefill is mid-flight is
            # observed HERE — between chunks — not after the full prompt:
            # the slot and its private blocks come back immediately (the
            # blocks it already published stay cached and shareable)
            for slot, st in list(self._chunked.items()):
                if st.req.id in aborted:
                    self._cancel_chunked(slot)
        self._admit()
        finished_pre: list[GenRequest] = []
        if self.spec is not None and self._active:
            if all(r.sampling.temperature == 0
                   for r in self._active.values()):
                # speculative path: flush any pipelined chunk first (its
                # tokens are this step's draft context), then one
                # draft+verify round — synchronous by construction, the
                # drafter needs the committed tokens back
                if self._inflight is not None:
                    prev, self._inflight = self._inflight, None
                    finished_pre = self._process_chunk(prev)
                if not self._active:
                    return finished_pre
                spec_finished = self._spec_step()
                if spec_finished is not None:
                    return finished_pre + spec_finished
                # no stream drafted anything: a width-1 verify would
                # commit ONE token per dispatch — plain multistep decode
                # commits chunk_len. Fall through to it (counted), so
                # the drafterless worst case stays AT decode throughput,
                # never below it.
                self.sched.note_spec_undrafted()
            else:
                # a non-greedy request in the batch: speculative
                # acceptance is only exact for greedy, so this dispatch
                # runs the normal decode path (counted — a quiet
                # fallback would read as a silent speedup regression)
                self.sched.note_spec_fallback()
        new_inflight = None
        if self._active and self._need_dispatch():
            active_mask = np.zeros((self.max_batch,), bool)
            temp = np.zeros((self.max_batch,), np.float32)
            top_k = np.zeros((self.max_batch,), np.int32)
            top_p = np.ones((self.max_batch,), np.float32)
            for slot, req in self._active.items():
                active_mask[slot] = True
                temp[slot] = req.sampling.temperature
                top_k[slot] = req.sampling.top_k
                top_p[slot] = req.sampling.top_p
            if self._inflight is None or self._fresh.all():
                token_in = jnp.asarray(self._tokens)
            else:
                # device carry from the in-flight chunk; fresh host tokens
                # (admissions since that dispatch) override their slots
                token_in = self._merge_tok(
                    self._inflight["next"], jnp.asarray(self._tokens),
                    jnp.asarray(self._fresh))
            self._fresh[:] = False
            tab = self._dispatch_tables()
            chunk_len = self.sched.decode_chunk_len(
                self._min_deterministic_remaining(),
                pressure=bool(self._waiting))
            self.sched.note_decode_dispatch(chunk_len)
            dspan = self._dispatch_span(
                "decode.step", [r for _, r in self._active.items()],
                chunk_len=chunk_len, batch=len(self._active))
            self._rng, step_rng = jax.random.split(self._rng)
            # static: an all-greedy batch skips the per-step full-vocab
            # sort (two compile variants total)
            greedy_only = not bool((temp > 0).any())
            if (self._compiled_decode is not None and greedy_only
                    and chunk_len == self.decode_chunk):
                # the precompile()d executable (depot fast path): same
                # program as the jitted call below, acquired without a
                # cold compile on a scale-up replica
                toks, lps, next_tok, self.cache = self._compiled_decode(
                    self.params, token_in, self.cache, jnp.asarray(tab),
                    jnp.asarray(active_mask), jnp.asarray(temp),
                    jnp.asarray(top_k), jnp.asarray(top_p), step_rng)
            else:
                toks, lps, next_tok, self.cache = self._decode(
                    self.params, token_in, self.cache, jnp.asarray(tab),
                    jnp.asarray(active_mask), jnp.asarray(temp),
                    jnp.asarray(top_k), jnp.asarray(top_p), step_rng,
                    greedy_only=greedy_only,
                    kernel=self.kernel, chunk_len=chunk_len)
            new_inflight = {
                "toks": toks, "lps": lps, "next": next_tok,
                "chunk_len": chunk_len, "span": dspan,
                # snapshot: tokens belong to the requests active at
                # DISPATCH time — a slot may host a new request by the
                # time these arrays are read back
                "snapshot": list(self._active.items()),
            }
        prev, self._inflight = self._inflight, new_inflight
        finished = self._process_chunk(prev) if prev is not None else []
        if not self.decode_pipeline and self._inflight is not None:
            # synchronous mode: flush immediately (no overlap, no lag)
            flush, self._inflight = self._inflight, None
            finished += self._process_chunk(flush)
        return finished_pre + finished

    def _need_dispatch(self) -> bool:
        """Skip the next dispatch when the in-flight chunk already covers
        every active request's remaining budget — kills the tail-overshoot
        chunk for uniform max_tokens batches."""
        if self._inflight is None:
            return True
        snapshot_reqs = {id(r) for _, r in self._inflight["snapshot"]}
        chunk = self._inflight["chunk_len"]
        for _, req in self._active.items():
            if id(req) not in snapshot_reqs:
                return True            # admitted after the dispatch
            if (len(req.generated) + chunk < req.sampling.max_tokens
                    and len(req.prompt) + len(req.generated) + chunk
                    < self.max_seq):
                return True            # still needs tokens past the chunk
        return False

    def _min_deterministic_remaining(self) -> Optional[int]:
        """Earliest DETERMINISTIC finish (max_tokens / max_seq bound)
        among active requests, net of tokens the in-flight chunk will
        already have produced — the boundary the adaptive decode chunk
        trims to so a freeing slot rejoins mid-chunk, not decode_chunk
        device steps later. EOS finishes are not predictable and don't
        count."""
        snapshot_reqs = (
            {id(r) for _, r in self._inflight["snapshot"]}
            if self._inflight is not None else set())
        pending = (self._inflight["chunk_len"]
                   if self._inflight is not None else 0)
        rem = None
        for _, req in self._active.items():
            r = min(req.sampling.max_tokens - len(req.generated),
                    self.max_seq - len(req.prompt) - len(req.generated))
            if id(req) in snapshot_reqs:
                r -= pending
            r = max(1, r)
            rem = r if rem is None else min(rem, r)
        return rem

    def _commit_token(self, req, slot: int, tok: int, lp: float) -> bool:
        """Append ONE committed token and report whether it finishes the
        request (eos / stop ids / max_tokens / max_seq) — the single
        stop-semantics implementation shared by the decode read-back,
        the speculative commit loop and admission, so the paths can
        never drift on what ends a generation."""
        req.generated.append(tok)
        req.logprobs.append(lp)
        self.generated_tokens += 1
        self._tokens[slot] = tok
        eos = req.sampling.eos_id
        return ((eos is not None and tok == eos)
                or tok in req.sampling.stop_token_ids
                or len(req.generated) >= req.sampling.max_tokens
                or len(req.prompt) + len(req.generated) >= self.max_seq)

    def _retire(self, req, slot: int) -> None:
        """Finish a request and free its slot (guarded: the slot may
        already host a newer request when retiring from a stale
        dispatch snapshot)."""
        req.done = True
        req.t_done = time.time()
        if not req.aborted and req.t_enqueue:
            self.request_hists["e2e"].observe(req.t_done - req.t_enqueue)
        if self._active.get(slot) is req:
            del self._active[slot]
            self.paged.release(slot)
            self._free.append(slot)

    def _dispatch_tables(self):
        """Block tables for a decode/verify dispatch: mid-prefill slots'
        rows zeroed so their idle scatter lands in the scratch block,
        never a half-prefilled prompt block."""
        tab = self.paged.tables
        if self._chunked:
            tab = tab.copy()
            for s in self._chunked:
                tab[s] = 0
        return tab

    def _process_chunk(self, inflight: dict) -> list[GenRequest]:
        toks = np.asarray(inflight["toks"])     # [chunk, B] (blocks here)
        lps = np.asarray(inflight["lps"])
        self.steps += toks.shape[0]
        finished = []
        committed_total = 0
        for slot, req in inflight["snapshot"]:
            if req.done:
                continue               # aborted/retired after dispatch
            n0 = len(req.generated)
            done = False
            for t in range(toks.shape[0]):
                if self._commit_token(req, slot, int(toks[t, slot]),
                                      float(lps[t, slot])):
                    # overshoot tokens beyond this point are trimmed (never
                    # appended); their cache writes went to this slot's own
                    # blocks / scratch and are ordered before any reuse
                    done = True
                    break
            n_new = len(req.generated) - n0
            committed_total += n_new
            self._note_request_latency(req, n_new)
            if done:
                finished.append(req)
                self._retire(req, slot)
        span = inflight.get("span")
        if span is not None:
            # the decode span covers dispatch -> read-back (pipelined:
            # device compute + the host overlap it bought)
            self.obs.end(span, tokens_committed=committed_total,
                         device_steps=int(toks.shape[0]))
        return finished

    def _spec_step(self) -> list[GenRequest]:
        """One speculative draft+verify round over the active batch.

        The drafter proposes up to spec_k tokens per stream from its own
        committed context; ONE verify dispatch writes all candidate KV
        rows (tail rows masked to scratch exactly like mid-prefill pad
        rows) and returns the target's greedy chain + logprobs; the
        longest draft prefix matching that chain commits, plus the
        target's own next token — so every round commits >= 1 token and
        greedy output is token-identical to plain decode. cache["len"]
        advances host-side by the COMMITTED count only: rejected rows
        sit beyond it, invisible to attention, and the next dispatch
        rewrites them before they could ever be unmasked."""
        bs = self.paged.block_size
        drafts: dict[int, list[int]] = {}
        k_max = 0
        vspan = None
        for slot, req in self._active.items():
            # deterministic remaining budget: drafts past it can never
            # commit (the commit loop stops at max_tokens/max_seq), so
            # they would only widen the verify batch for nothing
            rem = min(req.sampling.max_tokens - len(req.generated),
                      self.max_seq - len(req.prompt) - len(req.generated))
            d = self.spec.draft(req.prompt + req.generated)[:max(0, rem - 1)]
            drafts[slot] = d
            k_max = max(k_max, len(d))
        if k_max == 0:
            return None           # nothing to verify: caller runs decode
        # pow2 verify width (input column + drafts): log2(spec_k+1)
        # compile variants, the scheduler's static chunk_len scheme
        width = ceil_pow2(1 + k_max)
        tokens = np.zeros((self.max_batch, width), np.int32)
        limit = np.zeros((self.max_batch,), np.int32)
        for slot, req in self._active.items():
            tokens[slot, 0] = self._tokens[slot]
            d = drafts[slot]
            tokens[slot, 1:1 + len(d)] = d
            # rows at/after the slot's reserved tokens scatter to scratch
            limit[slot] = len(self.paged.slot_blocks(slot)) * bs
        self.sched.note_spec_dispatch(
            sum(len(d) for d in drafts.values()))
        vspan = self._dispatch_span(
            "decode.verify", [r for _, r in self._active.items()],
            width=width, drafted=sum(len(d) for d in drafts.values()),
            batch=len(self._active))
        toks, lps, self.cache = self._verify(
            self.params, jnp.asarray(tokens), self.cache,
            jnp.asarray(self._dispatch_tables()), jnp.asarray(limit))
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        self.steps += 1
        finished = []
        committed_total = 0
        new_len = np.zeros((self.max_batch,), np.int32)
        for slot, req in list(self._active.items()):
            if req.done:
                continue               # aborted after dispatch
            d = drafts[slot]
            # acceptance: walk the target's greedy chain; position i's
            # token commits, and matching draft i validates position i+1
            accepted = 0
            committed: list[tuple[int, float]] = []
            for i in range(len(d) + 1):
                committed.append((int(toks[slot, i]),
                                  float(lps[slot, i])))
                if i < len(d) and d[i] == committed[-1][0]:
                    accepted += 1
                    continue
                break
            n_appended = 0
            done = False
            for tok, lp in committed:
                n_appended += 1
                if self._commit_token(req, slot, tok, lp):
                    done = True
                    break
            # count only draft tokens that actually COMMITTED: an early
            # stop (eos/budget) truncates acceptance too, or the counter
            # would overstate the drafter on eos-heavy traffic
            self.sched.note_spec_result(min(accepted, n_appended),
                                        n_appended)
            committed_total += n_appended
            self._note_request_latency(req, n_appended)
            if done:
                finished.append(req)
                self._retire(req, slot)
            else:
                # committed length only — rejected rows stay beyond it
                new_len[slot] = len(req.prompt) + len(req.generated) - 1
        self.cache = self._set_lens(self.cache, jnp.asarray(new_len))
        if vspan is not None:
            self.obs.end(vspan, tokens_committed=committed_total)
        return finished

    def generate(self, prompts: Sequence[Sequence[int]],
                 sampling: Optional[SamplingParams] = None,
                 ) -> list[GenRequest]:
        """Synchronous batch API: submit all, step until drained."""
        reqs = [self.add_request(p, sampling) for p in prompts]
        while self.has_work():
            self.step()
        return reqs

    # ---------------- internals ----------------

    def _start_chunked(self, req, slot: int, n_shared: int) -> None:
        """Begin streaming a long prompt through chunked prefill. Chunks
        whose every row is radix-shared are skipped outright (the shared
        KV is already resident) — a fully-cached long prompt costs ONE
        chunk (the final one, for its last-row logits)."""
        L = len(req.prompt)
        W = self._chunk_width
        share_len = n_shared * self.paged.block_size
        start = min((share_len // W) * W, ((L - 1) // W) * W)
        self._chunked[slot] = _ChunkedPrefill(
            req=req, offset=start, share_len=share_len,
            tables=jnp.asarray(self.paged.tables))
        self.sched.note_chunked_started()

    def _advance_chunked(self, slot: int) -> int:
        """One prefill chunk for the slot's in-flight long prompt; the
        final chunk also runs the lm head + first-token sample and
        publishes the slot's cache len (making the sequence visible to
        decode). Completed full blocks publish to the radix tree after
        every chunk. Returns the budget tokens consumed."""
        st = self._chunked[slot]
        req = st.req
        L = len(req.prompt)
        W = self._chunk_width
        pspan = self._dispatch_span(
            "prefill.chunk", [req], slot=slot, offset=st.offset,
            width=W, prompt_tokens=L)
        piece = np.zeros((1, W), np.int32)
        part = req.prompt[st.offset:st.offset + W]
        piece[0, :len(part)] = part
        chunk_fn = self._compiled_prefill_chunk or self._prefill_chunk
        st.x_last, self.cache = chunk_fn(
            self.params, jnp.asarray(piece), self.cache, st.tables,
            jnp.int32(slot), jnp.int32(st.offset), jnp.int32(L),
            jnp.int32(st.share_len))
        st.offset += W
        self.sched.note_prefill_chunk(W)
        self.obs.end(pspan, final=st.offset >= L)
        # publish completed read-only blocks: every position < offset is
        # written and its write DISPATCHED, so a later sharer's reads are
        # device-ordered behind the content
        self.paged.publish_prompt_blocks(slot, req.prompt,
                                         min(st.offset, L))
        if st.offset >= L:
            logits = self._chunk_lm_head(self.params, st.x_last)
            tok, lp = self._sample_rows(logits, [req])
            self.cache = self._set_len(
                self.cache, jnp.int32(L), jnp.int32(slot))
            del self._chunked[slot]
            self.sched.note_chunked_admitted()
            self._post_admit(req, slot, int(tok[0]), float(lp[0]))
        return W

    def _chunked_phase(self, interleave: bool, budget: int,
                       spent: int) -> int:
        """Advance in-flight chunked prefills, oldest first: ONE chunk
        per step when interleaving, to completion otherwise (the legacy
        convoy) — aborts observed between chunks either way. Returns the
        updated budget spend. The single policy loop for both the
        resumed-prefill and fresh-start paths in _admit."""
        while self._chunked and (spent < budget or not interleave):
            slot = next(iter(self._chunked))
            if self._chunked[slot].req.aborted:
                self._cancel_chunked(slot)
                continue
            spent += self._advance_chunked(slot)
            if interleave:
                break      # one chunk per step while one is in flight
        return spent

    def _cancel_chunked(self, slot: int) -> None:
        """Abort/preempt a mid-flight chunked prefill: the slot and its
        private blocks return immediately; blocks it already published
        stay cached (their KV is valid — a pure function of the tokens)."""
        del self._chunked[slot]
        self.paged.release(slot)
        self._free.append(slot)
        self.sched.note_preempt()

    def _admit(self) -> None:
        """The scheduler's prefill phase: spend this step's token quota on
        prefill UNITS — one chunk of the oldest in-flight chunked prefill
        first (FIFO), then admissions — and stop once the quota is spent
        (the first unit always runs, so progress is guaranteed). Decode
        dispatch follows in step(), so a long prompt can never convoy the
        live streams. With ``interleave_prefill=False`` chunked prompts
        run to completion inside one step (the legacy convoy, kept as the
        scheduler-off baseline), still abort-checked between chunks."""
        from kubeflow_tpu.serving.paged_kv import blocks_for

        bs = self.paged.block_size
        budget = self.sched.prefill_budget()
        interleave = self.sched.cfg.interleave_prefill
        # in-flight chunked prefills have priority, oldest first
        spent = self._chunked_phase(interleave, budget, 0)
        if self._chunked and interleave:
            # a long prompt is mid-prefill: admissions wait their turn
            # behind it (FIFO start order), decode proceeds regardless
            return
        while spent < budget or spent == 0:
            with self._lock:
                if not self._waiting or not self._free:
                    return
                req = self._waiting.pop(0)
                slot = self._free.pop()
            # reserve the blocks this request can ever touch; when the pool
            # is exhausted the request waits at the HEAD of the queue (FIFO
            # under memory pressure — later arrivals must not starve it).
            # Full prompt blocks already cached (same tokens, same
            # positions) are SHARED, not recomputed storage — including for
            # chunked prompts, whose private full blocks publish chunk by
            # chunk (defer_publish) instead of at reserve time
            chunked = len(req.prompt) > self.buckets[-1]
            n_shared = self.paged.reserve(
                slot, len(req.prompt), req.sampling.max_tokens,
                min_blocks=blocks_for(len(req.prompt), bs),
                prompt=req.prompt, defer_publish=chunked)
            if n_shared is None:
                with self._lock:
                    self._waiting.insert(0, req)
                self._free.append(slot)
                self.sched.note_stall()
                return
            self._end_queue_span(req, slot, n_shared)
            if chunked:
                self._start_chunked(req, slot, n_shared)
                spent = self._chunked_phase(interleave, budget, spent)
                if self._chunked and interleave:
                    return
                continue
            # batched admission: take the FIFO prefix of same-bucket
            # requests and pay ONE prefill+insert+sample dispatch for all
            # of them (admission is RTT-bound on a remote chip)
            bucket = _bucket(len(req.prompt), self.buckets)
            batch = [(req, slot, n_shared)]
            while len(batch) < self.max_batch:
                with self._lock:
                    if not self._waiting or not self._free:
                        break
                    nxt = self._waiting[0]
                    if len(nxt.prompt) > self.buckets[-1] or \
                            _bucket(len(nxt.prompt),
                                    self.buckets) != bucket:
                        break
                    self._waiting.pop(0)
                    s2 = self._free.pop()
                ns2 = self.paged.reserve(
                    s2, len(nxt.prompt), nxt.sampling.max_tokens,
                    min_blocks=blocks_for(len(nxt.prompt), bs),
                    prompt=nxt.prompt)
                if ns2 is None:
                    with self._lock:
                        self._waiting.insert(0, nxt)
                    self._free.append(s2)
                    self.sched.note_stall()
                    break
                self._end_queue_span(nxt, s2, ns2)
                batch.append((nxt, s2, ns2))
            self._admit_prefill_batch(batch, bucket)
            self.sched.note_admitted(len(batch))
            spent += bucket * len(batch)

    def _admit_prefill_batch(self, batch, bucket: int) -> None:
        """One prefill + insert + first-token sample for a same-bucket
        admission batch. Rows pad to the next power of two (compile count
        log2(max_batch) per bucket) so the steady-state single-request
        admission does ~1 row of work, not max_batch rows; pad rows carry
        slot -1 and their writes land in the scratch block / are dropped."""
        from kubeflow_tpu.serving.paged_kv import blocks_for

        bs = self.paged.block_size
        width = min(self.max_batch, 1 << (len(batch) - 1).bit_length())
        nbmax = bucket // bs
        toks = np.zeros((width, bucket), np.int32)
        # pad rows: length 0 — prefill masks them out of MoE routing and
        # clamps its logit-gather index, so they never influence real rows
        lengths = np.zeros((width,), np.int32)
        blk = np.zeros((width, nbmax), np.int32)
        slots = np.full((width,), -1, np.int32)
        for i, (req, slot, n_shared) in enumerate(batch):
            toks[i, :len(req.prompt)] = req.prompt
            lengths[i] = len(req.prompt)
            nb_prefill = blocks_for(len(req.prompt), bs)
            ids = self.paged.slot_blocks(slot)
            blk[i, n_shared:nb_prefill] = ids[n_shared:nb_prefill]
            slots[i] = slot
        scratch = llama.init_cache(self.cfg, width, bucket)
        self.prefill_dispatches += 1
        pspan = self._dispatch_span(
            "prefill.batch", [r for r, _, _ in batch],
            bucket=bucket, batch=len(batch))
        logits, filled = self._prefill(
            self.params, jnp.asarray(toks), jnp.asarray(lengths), scratch)
        self.cache = self._insert_batch(
            self.cache, filled["k"], filled["v"], jnp.asarray(blk),
            jnp.asarray(lengths), jnp.asarray(slots))
        tok, lp = self._sample_rows(logits, [r for r, _, _ in batch],
                                    width=width)
        self.obs.end(pspan)
        for i, (req, slot, _) in enumerate(batch):
            self._post_admit(req, slot, int(tok[i]), float(lp[i]))

    def _sample_rows(self, logits, reqs, width: Optional[int] = None):
        """First-token sampling for admission rows (one jitted call)."""
        width = width or len(reqs)
        temp = np.zeros((width,), np.float32)
        top_k = np.zeros((width,), np.int32)
        top_p = np.ones((width,), np.float32)
        for i, r in enumerate(reqs):
            temp[i] = r.sampling.temperature
            top_k[i] = r.sampling.top_k
            top_p[i] = r.sampling.top_p
        self._rng, rng = jax.random.split(self._rng)
        tok, lp = self._first_sample(
            logits, rng, jnp.asarray(temp), jnp.asarray(top_k),
            jnp.asarray(top_p))
        return np.asarray(tok), np.asarray(lp)

    def _post_admit(self, req, slot: int, first_tok: int,
                    first_lp: float) -> None:
        """Per-request bookkeeping after its KV is resident: the
        prefill-sampled token is generation token #1; decode continues
        from it (or the request finishes instantly on eos/budget —
        the same _commit_token stop semantics as every other path)."""
        req.slot = slot
        self._fresh[slot] = True       # override any device token carry
        self._active[slot] = req
        done = self._commit_token(req, slot, first_tok, first_lp)
        self._note_request_latency(req, 1)       # TTFT closes here
        if done:
            self._retire(req, slot)
        elif req.hold_after_prefill:
            # disagg prefill tier: the prefill is complete and token #1
            # sampled — park the request for export_held_kv instead of
            # decoding. The slot stays allocated and its blocks stay
            # refcount-pinned (PREFILL_OWNED in the handoff state machine)
            # until release_held transfers or drops ownership.
            del self._active[slot]
            self._held[slot] = req
