"""Serving API types — InferenceService / ServingRuntime / InferenceGraph.

Parity with the reference's KServe API surface (SURVEY.md §2.4: predictor/
transformer/explainer specs, canary traffic %, min/max replicas,
ServingRuntime matched by model format, InferenceGraph DAG, TrainedModel
multi-model), TPU-first: runtimes request TPU slices by topology and carry
an AOT-compile/warmup contract instead of GPU resource counts.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional

from kubeflow_tpu.api.types import TPUSpec
# The predictor-spec view of the continuous-batching step scheduler
# (serving/scheduler.py is pure stdlib, so the control plane can carry it
# without importing jax): per-step prefill token quota, chunked-prefill
# interleaving, adaptive decode-chunk trims, radix prefix cache, and the
# speculative-decoding knobs (spec_decode / spec_k / spec_drafter).
from kubeflow_tpu.serving.scheduler import SchedulerConfig as SchedulerPolicy
# The predictor-spec view of the quantized-serving config (also pure
# stdlib): KV dtype, weight dtype, exact-parity escape hatch — stamped as
# KFT_QUANT_* onto the predictor pod by the ISVC controller.
from kubeflow_tpu.serving.scheduler import QuantConfig as QuantPolicy


@dataclasses.dataclass
class ModelFormat:
    name: str                      # e.g. "llama", "sklearn", "jax-saved"
    version: Optional[str] = None


@dataclasses.dataclass
class ServingRuntime:
    """Template for a runtime pod serving one or more model formats
    (ClusterServingRuntime when namespace is None)."""

    name: str
    supported_formats: list[ModelFormat]
    image: str = "kubeflow-tpu/serving:latest"
    command: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    tpu: Optional[TPUSpec] = None
    namespace: Optional[str] = None     # None => cluster-scoped
    priority: int = 0                   # higher wins on multi-match
    # TPU cold-start contract: persistent XLA compile cache + warmup shapes
    compile_cache_dir: Optional[str] = None
    warmup_shapes: list[list[int]] = dataclasses.field(default_factory=list)

    def supports(self, fmt: ModelFormat) -> bool:
        return any(
            f.name == fmt.name and
            (f.version is None or fmt.version is None or
             f.version == fmt.version)
            for f in self.supported_formats
        )


@dataclasses.dataclass
class CanarySLO:
    """Promotion gate for a canary revision (serving/controller.CanaryGate
    consumes it): promote once ``min_requests`` canary outcomes stayed
    within the error-rate (and optional p95 latency) budget; roll back the
    moment the error budget is provably burned."""

    max_error_rate: float = 0.02
    max_p95_latency_s: float = 0.0      # 0 = don't gate on latency
    min_requests: int = 20


# Role-default autoscale signal per tier: prefill is throughput-bound on
# queued prompt tokens, decode is residency-bound on occupied slots.
# TierSpec.scale_metric "" resolves through this map (the Autoscaler side);
# serving/disagg.py re-exports the same two names to the data plane.
TIER_DEFAULT_SCALE_METRIC = {
    "prefill": "token_backlog",
    "decode": "occupancy_slots",
}


@dataclasses.dataclass
class TierSpec:
    """One tier of a DISAGGREGATED predictor (serving/disagg.py): the
    controller materialises a pod set per tier — same model, same
    revision, tier-scoped depot keys — and the Autoscaler scales each
    tier independently on its own ``kft_model_sched_*`` signal.

    ``scale_metric`` "" picks the role default (prefill scales on
    ``token_backlog``, decode on ``occupancy_slots``); ``scale_target``
    0 inherits the predictor-level target. ``scheduler``/``quant``
    override the predictor-level policies for this tier only (e.g. a
    bigger prefill token quota on the prefill tier)."""

    name: str                            # "prefill" | "decode"
    min_replicas: int = 1
    max_replicas: int = 1
    scale_metric: str = ""               # "" = role default
    scale_target: int = 0                # 0 = inherit predictor target
    scheduler: Optional[SchedulerPolicy] = None
    quant: Optional[QuantPolicy] = None
    env: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PredictorSpec:
    model_format: ModelFormat = dataclasses.field(
        default_factory=lambda: ModelFormat("jax"))
    storage_uri: Optional[str] = None
    runtime: Optional[str] = None       # explicit ServingRuntime name
    min_replicas: int = 1
    max_replicas: int = 1
    # "sched" (default) = the per-replica kft_model_sched_* family (queue
    # depth / token backlog / occupancy — what the fleet Autoscaler
    # consumes; pods exporting none fall back to the in-flight probe);
    # "concurrency" pins the legacy in-flight probe. scale_target is
    # slots (or in-flight requests) per replica either way.
    scale_metric: str = "sched"
    scale_target: int = 8
    canary_traffic_percent: Optional[int] = None   # % to the LATEST revision
    canary_slo: Optional[CanarySLO] = None         # SLO-gated promotion
    tpu: Optional[TPUSpec] = None
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    # LLM runtimes only: step-scheduler knobs, stamped onto the predictor
    # pod as KFT_PREFILL_QUOTA / KFT_INTERLEAVE_PREFILL /
    # KFT_ADAPTIVE_DECODE_CHUNK / KFT_RADIX_CACHE / KFT_SPEC_DECODE /
    # KFT_SPEC_K / KFT_SPEC_DRAFTER by the ISVC controller
    scheduler: Optional[SchedulerPolicy] = None
    # quantized serving, stamped as KFT_QUANT_KV / KFT_QUANT_WEIGHTS /
    # KFT_QUANT_EXACT_PARITY by the ISVC controller; resolution (platform
    # support, downgrade counting) happens in the replica's engine
    quant: Optional[QuantPolicy] = None
    # disaggregated serving: non-empty => the controller materialises one
    # pod set PER TIER (KFT_TIER-stamped, decode pods also get
    # KFT_KV_BIND) instead of the single co-located predictor set, and
    # min/max_replicas above are ignored in favor of the per-tier bounds
    tiers: list[TierSpec] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ComponentSpec:
    """Transformer or explainer container spec."""

    image: str = "kubeflow-tpu/serving:latest"
    command: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    min_replicas: int = 1
    max_replicas: int = 1


@dataclasses.dataclass
class InferenceServiceStatus:
    ready: bool = False
    url: Optional[str] = None
    latest_revision: int = 0
    ready_revision: int = 0
    traffic: dict[int, int] = dataclasses.field(default_factory=dict)
    conditions: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class InferenceService:
    name: str
    predictor: PredictorSpec
    transformer: Optional[ComponentSpec] = None
    explainer: Optional[ComponentSpec] = None
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    status: InferenceServiceStatus = dataclasses.field(
        default_factory=InferenceServiceStatus)
    generation: int = 0           # bumped on every spec change


def _parse_scheduler(sched):
    if isinstance(sched, dict):
        sched = dict(sched)
        sq = sched.pop("quant", None)
        if isinstance(sq, dict):
            sq = QuantPolicy(**sq)
        sched = SchedulerPolicy(**sched)
        sched.quant = sq
    return sched


def _parse_tier(t) -> TierSpec:
    if isinstance(t, TierSpec):
        return t
    t = dict(t)
    t["scheduler"] = _parse_scheduler(t.get("scheduler"))
    tq = t.get("quant")
    if isinstance(tq, dict):
        t["quant"] = QuantPolicy(**tq)
    return TierSpec(**t)


def inference_service_from_dict(d: dict) -> InferenceService:
    """JSON -> InferenceService (the operator's POST body; the apiserver
    deserialization role). Only the predictor surface — transformer/explainer
    specs are applied programmatically."""
    p = dict(d.get("predictor", {}))
    fmt = p.pop("model_format", "jax")
    if isinstance(fmt, dict):
        fmt = ModelFormat(**fmt)
    else:
        fmt = ModelFormat(str(fmt))
    tpu = p.pop("tpu", None)
    if isinstance(tpu, dict):
        tpu = TPUSpec(**tpu)
    sched = _parse_scheduler(p.pop("scheduler", None))
    quant = p.pop("quant", None)
    if isinstance(quant, dict):
        quant = QuantPolicy(**quant)
    slo = p.pop("canary_slo", None)
    if isinstance(slo, dict):
        slo = CanarySLO(**slo)
    tiers = [_parse_tier(t) for t in (p.pop("tiers", None) or [])]
    predictor = PredictorSpec(model_format=fmt, tpu=tpu, scheduler=sched,
                              quant=quant, canary_slo=slo, tiers=tiers,
                              **p)
    return InferenceService(
        name=d["name"], namespace=d.get("namespace", "default"),
        labels=dict(d.get("labels", {})), predictor=predictor)


# ---------------------------------------------------------------- graph ----

class GraphNodeType(str, enum.Enum):
    SEQUENCE = "Sequence"
    SWITCH = "Switch"
    ENSEMBLE = "Ensemble"
    SPLITTER = "Splitter"


@dataclasses.dataclass
class GraphStep:
    """One routing target inside a node: an InferenceService name or another
    graph node."""

    service: Optional[str] = None       # InferenceService / model name
    node: Optional[str] = None          # nested node name
    condition: Optional[str] = None     # Switch: matched against request data
    weight: int = 100                   # Splitter: traffic weight
    data: str = "$request"              # Sequence: "$request" or "$response"

    def target(self) -> str:
        return self.service or self.node or ""


@dataclasses.dataclass
class GraphNode:
    router_type: GraphNodeType
    steps: list[GraphStep]


@dataclasses.dataclass
class InferenceGraph:
    name: str
    nodes: dict[str, GraphNode]         # must contain "root"
    namespace: str = "default"

    def validate(self) -> None:
        if "root" not in self.nodes:
            raise ValueError("inference graph needs a 'root' node")
        for name, node in self.nodes.items():
            if not node.steps:
                raise ValueError(f"graph node {name!r} has no steps")
            for s in node.steps:
                if s.node is not None and s.node not in self.nodes:
                    raise ValueError(
                        f"node {name!r} references unknown node {s.node!r}")
                if not s.target():
                    raise ValueError(f"node {name!r} has an empty step")


@dataclasses.dataclass
class TrainedModel:
    """Multi-model: attach a model to an existing InferenceService's
    runtime (the model-repository hot-load path)."""

    name: str
    inference_service: str
    model_format: ModelFormat = dataclasses.field(
        default_factory=lambda: ModelFormat("jax"))
    storage_uri: Optional[str] = None
    namespace: str = "default"
