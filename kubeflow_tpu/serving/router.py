"""Graph router, traffic splitter and the FLEET router — request routing
for one replica set (the reference's InferenceGraph router and Knative
revision traffic split, SURVEY.md §2.4) plus the multi-replica layer:
prefix-affine consistent-hash load balancing so the radix prefix cache
(serving/paged_kv.py) keeps hitting when one model serves from N replicas.

Why prefix-affine: the radix cache keys KV blocks by token tuples, so two
requests only share if the SAME replica saw both. Random/least-loaded
routing dilutes every shared prefix N ways (each replica pays its own cold
miss for each tenant's system prompt); hashing on the prompt's leading
radix-block key sends all sharers of a prefix to one replica, preserving
the single-replica hit rate. Bounded-load spill (the "power of
consistent-hashing with bounded loads" rule) caps the hot-prefix downside:
when the affine replica's queue depth exceeds a threshold, the request
walks to the next distinct node on the ring instead of queueing behind the
hot spot.
"""

from __future__ import annotations

import bisect
import hashlib
import random
import threading
from typing import Callable, Optional, Union

from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.serving.model import Model, ModelRepository
from kubeflow_tpu.serving.protocol import InferRequest, InferResponse
from kubeflow_tpu.serving.server import InferenceClient
from kubeflow_tpu.serving.types import (
    GraphNode, GraphNodeType, GraphStep, InferenceGraph,
)

Backend = Union[Model, InferenceClient, Callable[[InferRequest], InferResponse]]


def _call(backend: Backend, request: InferRequest) -> InferResponse:
    if isinstance(backend, Model):
        return backend(request)
    if isinstance(backend, InferenceClient):
        return backend.infer(request)
    return backend(request)


def _hash64(data: str) -> int:
    return int.from_bytes(
        hashlib.sha256(data.encode()).digest()[:8], "big")


def _stable_unit(request_id) -> float:
    """Deterministic uniform [0, 1) draw from a request id — the sticky
    half of the canary split: the SAME request (a client retry mid-
    rollout) must land on the SAME revision, not re-flip the coin."""
    return _hash64(f"req:{request_id}") / float(1 << 64)


def radix_block_key(prompt, block_size: int) -> tuple:
    """The prompt's leading radix-block key — the token tuple of its first
    FULL KV block, exactly the tuple ``RadixPrefixCache`` keys that block's
    node by (prompts shorter than one block key on what they have). Two
    prompts share cached prefix blocks only if these keys are equal, so
    this is the affinity unit fleet routing hashes on."""
    n = min(len(prompt), int(block_size))
    return tuple(int(t) for t in prompt[:n])


class HashRing:
    """Consistent-hash ring with virtual nodes. Adding/removing one node
    moves ~1/N of the key space and NOTHING else — the property that keeps
    a scale-up from flushing every replica's prefix cache at once."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: list[tuple[int, str]] = []      # sorted (hash, node)
        self._nodes: set[str] = set()

    def __len__(self) -> int:
        return len(self._nodes)

    def nodes(self) -> set:
        return set(self._nodes)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for v in range(self.vnodes):
            bisect.insort(self._points, (_hash64(f"{node}#{v}"), node))

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [p for p in self._points if p[1] != node]

    def walk(self, key) -> list[str]:
        """Distinct nodes in ring order starting at ``key``'s position:
        element 0 is the affine owner, the rest are the bounded-load
        spill order."""
        if not self._points:
            return []
        h = _hash64(f"key:{key!r}")
        i = bisect.bisect_right(self._points, (h, "￿"))
        seen: list[str] = []
        for j in range(len(self._points)):
            node = self._points[(i + j) % len(self._points)][1]
            if node not in seen:
                seen.append(node)
                if len(seen) == len(self._nodes):
                    break
        return seen

    def lookup(self, key) -> Optional[str]:
        order = self.walk(key)
        return order[0] if order else None


class FleetRouter:
    """Routes requests across model replicas, prefix-affine by default.

    ``pick(prompt)`` consistent-hashes the prompt's leading radix-block
    key onto the replica ring and returns the chosen replica name; when
    the affine replica's load (``load_of(name, backend)`` — queue depth by
    default) exceeds ``spill_queue_depth``, the request spills to the next
    ring node under the threshold (counted). When EVERY node is over the
    threshold the request stays on its affine replica (counted
    separately): bounded load protects against skew — one hot prefix
    drowning a replica while others idle — but under global saturation
    spilling buys no latency and would shred every tenant's cache
    affinity; that counter rising is the autoscaler's cue that the fleet
    is undersized, not misrouted.

    ``policy="random"`` is the ablation baseline the bench contrasts
    against: uniform routing, which dilutes every shared prefix N ways.
    """

    def __init__(self, *, block_size: int = 16, policy: str = "affine",
                 spill_queue_depth: int = 4, vnodes: int = 64,
                 load_of: Optional[Callable] = None, seed: int = 0,
                 obs: Optional[obs_trace.SpanCollector] = None):
        if policy not in ("affine", "random"):
            raise ValueError(f"policy={policy!r} (want affine|random)")
        # span collector: route() roots the request trace here (or chains
        # under an incoming traceparent) and propagates context downstream
        self.obs = obs or obs_trace.collector()
        self.block_size = int(block_size)
        self.policy = policy
        self.spill_queue_depth = int(spill_queue_depth)
        self.ring = HashRing(vnodes)
        self.replicas: dict[str, Backend] = {}
        self.load_of = load_of or self._default_load
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # counters (snapshot() exports them into bench JSON / tests)
        self.routed = 0
        self.spills = 0              # affine node over threshold, walked on
        self.spill_saturated = 0     # every node over threshold: least-loaded
        self.random_routes = 0
        self.routes_by_replica: dict[str, int] = {}

    @staticmethod
    def _default_load(name: str, backend) -> float:
        """Queue depth of a replica: engine-backed replicas report their
        scheduler queue; opaque backends read as unloaded (no spill)."""
        eng = getattr(backend, "engine", backend)
        stats = getattr(eng, "scheduler_stats", None)
        if stats is None:
            return 0.0
        snap = stats()
        return float(snap.get("queue_depth", 0)
                     + snap.get("chunked_in_flight", 0))

    # ------------------------------------------------------- membership --

    def add_replica(self, name: str, backend: Backend = None) -> None:
        with self._lock:
            self.replicas[name] = backend
            self.ring.add(name)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self.replicas.pop(name, None)
            self.ring.remove(name)

    # ------------------------------------------------------------ route --

    def pick(self, prompt, request_id=None) -> str:
        """Replica name for ``prompt``. Raises when the fleet is empty."""
        with self._lock:
            if not self.replicas:
                raise ValueError("fleet has no replicas")
            if self.policy == "random":
                if request_id is not None:
                    names = sorted(self.replicas)
                    name = names[int(_stable_unit(request_id)
                                     * len(names)) % len(names)]
                else:
                    name = self._rng.choice(sorted(self.replicas))
                spilled = saturated = False
            else:
                # membership snapshot under the lock; the (possibly
                # blocking — HTTP on real fleets) load probes run OUTSIDE
                # it, so one slow replica never serializes all routing
                order = self.ring.walk(
                    radix_block_key(prompt, self.block_size))
                backends = {n: self.replicas[n] for n in order}
        if self.policy == "random":
            pass
        else:
            name, spilled, saturated = self._pick_affine(order, backends)
        with self._lock:
            self.routed += 1
            if self.policy == "random":
                self.random_routes += 1
            if spilled:
                self.spills += 1
            if saturated:
                self.spill_saturated += 1
            self.routes_by_replica[name] = (
                self.routes_by_replica.get(name, 0) + 1)
        return name

    def _pick_affine(self, order, backends):
        """-> (name, spilled, saturated). Loads are probed LAZILY: the
        common no-spill case touches only the affine owner's load, not
        one probe per replica per request."""
        for i, name in enumerate(order):
            if self.load_of(name, backends[name]) \
                    <= self.spill_queue_depth:
                return name, i > 0, False
        # every replica over threshold (global saturation, not skew):
        # stay affine — spilling would shred cache affinity for zero
        # latency win. Counted: this rising is the scale-up cue.
        return order[0], False, True

    def route(self, request: InferRequest, prompt) -> InferResponse:
        """pick + call, for callers fronting real backends. A replica
        removed between pick and call (concurrent scale-down) re-picks
        onto the surviving fleet instead of failing the request.

        Tracing: this is where the request trace usually ROOTS — a
        router span opens (chained under any incoming traceparent),
        its context propagates to the backend via the ``traceparent``
        parameter + HTTP header, and the span closes with the replica
        that served (or the error) so a re-pick after a vanished
        replica is one coherent span, never an orphan chain."""
        span = self.obs.start(
            "router.route", parent=request.parameters.get("traceparent"),
            attrs={"policy": self.policy,
                   "prompt_tokens": len(prompt)})
        request.parameters["traceparent"] = span.traceparent()
        name = None
        try:
            for attempt in range(2):
                name = self.pick(prompt, request_id=request.id)
                with self._lock:
                    backend = self.replicas.get(name)
                if backend is not None:
                    resp = _call(backend, request)
                    self.obs.end(span, replica=name, repicked=attempt)
                    return resp
            raise KeyError(f"replica {name!r} vanished during routing")
        except BaseException as e:
            if span.t1 is None:
                self.obs.end(span, replica=name,
                             error=type(e).__name__)
            raise

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "policy": self.policy,
                "replicas": sorted(self.replicas),
                "routed": self.routed,
                "spills": self.spills,
                "spill_saturated": self.spill_saturated,
                "random_routes": self.random_routes,
                "routes_by_replica": dict(self.routes_by_replica),
            }


class TieredRouter:
    """Tier-aware routing for a disaggregated fleet (serving/disagg.py):
    admission lands on the PREFILL tier and the stream moves to a
    prefix-affine DECODE replica at handoff. Both tiers are full
    ``FleetRouter``s — the decode pick is prefix-affine so handoffs of
    shared-prefix tenants pile onto one pool (which is what makes the
    bypass rule fire), and the prefill pick is prefix-affine so repeat
    prefixes re-prefill against a warm radix tree.

    Bypass rule: when ``cached_blocks_of(decode_replica, prompt)``
    reports every FULL prompt block already radix-resident on the affine
    decode replica, the request skips the prefill tier entirely
    (``plan["bypass"]``) and admits on the decode replica as a normal
    request — the fully-shared prefix costs one chunk there, strictly
    cheaper than prefill + migration. Counted as ``prefill_bypasses``.
    """

    def __init__(self, *, block_size: int = 16,
                 spill_queue_depth: int = 4, vnodes: int = 64,
                 load_of: Optional[Callable] = None, seed: int = 0,
                 cached_blocks_of: Optional[Callable] = None):
        self.block_size = int(block_size)
        self.prefill = FleetRouter(
            block_size=block_size, spill_queue_depth=spill_queue_depth,
            vnodes=vnodes, load_of=load_of, seed=seed)
        self.decode = FleetRouter(
            block_size=block_size, spill_queue_depth=spill_queue_depth,
            vnodes=vnodes, load_of=load_of, seed=seed)
        self.cached_blocks_of = cached_blocks_of
        self._lock = threading.Lock()
        self.plans = 0
        self.handoffs_planned = 0
        self.prefill_bypasses = 0

    def router_for(self, tier: str) -> FleetRouter:
        if tier == "prefill":
            return self.prefill
        if tier == "decode":
            return self.decode
        raise ValueError(f"tier={tier!r} (want prefill|decode)")

    def add_replica(self, tier: str, name: str, backend=None) -> None:
        self.router_for(tier).add_replica(name, backend)

    def remove_replica(self, tier: str, name: str) -> None:
        self.router_for(tier).remove_replica(name)

    def plan(self, prompt, request_id=None) -> dict:
        """-> {"decode": name, "prefill": name|None, "bypass": bool}.
        ``prefill`` is None exactly when the bypass rule fired."""
        decode_name = self.decode.pick(prompt, request_id=request_id)
        full = len(prompt) // self.block_size
        bypass = False
        if full > 0 and self.cached_blocks_of is not None:
            try:
                bypass = self.cached_blocks_of(
                    decode_name, prompt) >= full
            except Exception:
                bypass = False      # a dead probe must not fail routing
        prefill_name = None
        if not bypass:
            prefill_name = self.prefill.pick(prompt,
                                             request_id=request_id)
        with self._lock:
            self.plans += 1
            if bypass:
                self.prefill_bypasses += 1
            else:
                self.handoffs_planned += 1
        return {"decode": decode_name, "prefill": prefill_name,
                "bypass": bypass}

    def snapshot(self) -> dict:
        with self._lock:
            out = {"plans": self.plans,
                   "handoffs_planned": self.handoffs_planned,
                   "prefill_bypasses": self.prefill_bypasses}
        out["prefill"] = self.prefill.snapshot()
        out["decode"] = self.decode.snapshot()
        return out


class GraphRouter:
    """Executes an InferenceGraph over named backends.

    Node semantics (matching the reference router):
    - Sequence: steps run in order; a step with data="$response" receives the
      previous step's outputs as its inputs.
    - Switch: first step whose ``condition`` equals the request's
      ``parameters['condition']`` runs; no match => error.
    - Ensemble: all steps run on the same request; outputs are concatenated
      (tensor names prefixed by step target).
    - Splitter: one step chosen by weight (canary between model versions).
    """

    def __init__(self, graph: InferenceGraph, backends: dict[str, Backend],
                 seed: int = 0):
        graph.validate()
        self.graph = graph
        self.backends = backends
        self._rng = random.Random(seed)

    def route(self, request: InferRequest) -> InferResponse:
        return self._run_node("root", request)

    def _run_node(self, name: str, request: InferRequest) -> InferResponse:
        node = self.graph.nodes[name]
        if node.router_type == GraphNodeType.SEQUENCE:
            return self._sequence(node, request)
        if node.router_type == GraphNodeType.SWITCH:
            return self._switch(node, request)
        if node.router_type == GraphNodeType.ENSEMBLE:
            return self._ensemble(node, request)
        if node.router_type == GraphNodeType.SPLITTER:
            return self._splitter(node, request)
        raise ValueError(f"unknown node type {node.router_type}")

    def _step(self, step: GraphStep, request: InferRequest) -> InferResponse:
        if step.node is not None:
            return self._run_node(step.node, request)
        backend = self.backends.get(step.service)
        if backend is None:
            raise KeyError(f"no backend for service {step.service!r}")
        return _call(backend, request)

    def _sequence(self, node: GraphNode, request: InferRequest
                  ) -> InferResponse:
        current = request
        response = None
        for step in node.steps:
            if step.data == "$response" and response is not None:
                current = InferRequest(
                    model_name=step.target(),
                    inputs=response.outputs,
                    id=request.id, parameters=request.parameters)
            response = self._step(step, current)
        return response

    def _switch(self, node: GraphNode, request: InferRequest) -> InferResponse:
        cond = request.parameters.get("condition")
        for step in node.steps:
            if step.condition is None or step.condition == cond:
                return self._step(step, request)
        raise ValueError(f"switch: no branch matches condition {cond!r}")

    def _ensemble(self, node: GraphNode, request: InferRequest
                  ) -> InferResponse:
        outputs = []
        for step in node.steps:
            resp = self._step(step, request)
            for t in resp.outputs:
                t.name = f"{step.target()}.{t.name}"
                outputs.append(t)
        return InferResponse(model_name=self.graph.name, outputs=outputs,
                             id=request.id)

    def _splitter(self, node: GraphNode, request: InferRequest
                  ) -> InferResponse:
        # sticky-deterministic canary split: a request WITH an id hashes
        # onto the weight line (a retry mid-rollout keeps its revision);
        # only id-less requests draw from the seeded RNG
        steps = [(s, s.weight) for s in node.steps]
        step = _pick_weighted(steps, request.id, self._rng)
        return self._step(step, request)


def _pick_weighted(items, request_id, rng: random.Random):
    """One weighted pick shared by the graph splitter and the revision
    splitter: deterministic on ``request_id`` when present, seeded-RNG
    otherwise. All-zero (or negative) total weight is a configuration
    error and raises — silently routing such traffic to the last entry
    hid dead canaries."""
    weights = [(item, max(0.0, float(w))) for item, w in items]
    total = sum(w for _, w in weights)
    if total <= 0:
        raise ValueError("traffic split has no positive weights")
    u = (_stable_unit(request_id) if request_id is not None
         else rng.random())
    pick = u * total
    acc = 0.0
    last_live = None
    for item, w in weights:
        if w <= 0:
            continue                 # a zero-weight step can never win
        last_live = item
        acc += w
        if pick <= acc:
            return item
    return last_live                 # float-accumulation edge at pick≈total


class TrafficSplitter:
    """Revision-level traffic split for canary rollout: routes a request to
    one of the revisions' backends per the InferenceService status traffic
    map (the ServingController maintains the map; this enforces it).
    ``request_id`` makes the pick sticky-deterministic — the same request
    retried mid-rollout cannot flip revisions."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def pick(self, traffic: dict[int, int], request_id=None) -> int:
        if not traffic:
            raise ValueError("no traffic targets")
        return _pick_weighted(sorted(traffic.items()), request_id,
                              self._rng)


def serve_repository(repository: ModelRepository) -> dict[str, Backend]:
    """Expose every model in a repository as router backends."""
    return {name: repository.get(name) for name in repository.names()}
