"""Graph router + traffic splitter — the reference's InferenceGraph router
and Knative revision traffic split (SURVEY.md §2.4) as one in-process router
that can front either local Models or remote InferenceClients.
"""

from __future__ import annotations

import random
from typing import Callable, Union

from kubeflow_tpu.serving.model import Model, ModelRepository
from kubeflow_tpu.serving.protocol import InferRequest, InferResponse
from kubeflow_tpu.serving.server import InferenceClient
from kubeflow_tpu.serving.types import (
    GraphNode, GraphNodeType, GraphStep, InferenceGraph,
)

Backend = Union[Model, InferenceClient, Callable[[InferRequest], InferResponse]]


def _call(backend: Backend, request: InferRequest) -> InferResponse:
    if isinstance(backend, Model):
        return backend(request)
    if isinstance(backend, InferenceClient):
        return backend.infer(request)
    return backend(request)


class GraphRouter:
    """Executes an InferenceGraph over named backends.

    Node semantics (matching the reference router):
    - Sequence: steps run in order; a step with data="$response" receives the
      previous step's outputs as its inputs.
    - Switch: first step whose ``condition`` equals the request's
      ``parameters['condition']`` runs; no match => error.
    - Ensemble: all steps run on the same request; outputs are concatenated
      (tensor names prefixed by step target).
    - Splitter: one step chosen by weight (canary between model versions).
    """

    def __init__(self, graph: InferenceGraph, backends: dict[str, Backend],
                 seed: int = 0):
        graph.validate()
        self.graph = graph
        self.backends = backends
        self._rng = random.Random(seed)

    def route(self, request: InferRequest) -> InferResponse:
        return self._run_node("root", request)

    def _run_node(self, name: str, request: InferRequest) -> InferResponse:
        node = self.graph.nodes[name]
        if node.router_type == GraphNodeType.SEQUENCE:
            return self._sequence(node, request)
        if node.router_type == GraphNodeType.SWITCH:
            return self._switch(node, request)
        if node.router_type == GraphNodeType.ENSEMBLE:
            return self._ensemble(node, request)
        if node.router_type == GraphNodeType.SPLITTER:
            return self._splitter(node, request)
        raise ValueError(f"unknown node type {node.router_type}")

    def _step(self, step: GraphStep, request: InferRequest) -> InferResponse:
        if step.node is not None:
            return self._run_node(step.node, request)
        backend = self.backends.get(step.service)
        if backend is None:
            raise KeyError(f"no backend for service {step.service!r}")
        return _call(backend, request)

    def _sequence(self, node: GraphNode, request: InferRequest
                  ) -> InferResponse:
        current = request
        response = None
        for step in node.steps:
            if step.data == "$response" and response is not None:
                current = InferRequest(
                    model_name=step.target(),
                    inputs=response.outputs,
                    id=request.id, parameters=request.parameters)
            response = self._step(step, current)
        return response

    def _switch(self, node: GraphNode, request: InferRequest) -> InferResponse:
        cond = request.parameters.get("condition")
        for step in node.steps:
            if step.condition is None or step.condition == cond:
                return self._step(step, request)
        raise ValueError(f"switch: no branch matches condition {cond!r}")

    def _ensemble(self, node: GraphNode, request: InferRequest
                  ) -> InferResponse:
        outputs = []
        for step in node.steps:
            resp = self._step(step, request)
            for t in resp.outputs:
                t.name = f"{step.target()}.{t.name}"
                outputs.append(t)
        return InferResponse(model_name=self.graph.name, outputs=outputs,
                             id=request.id)

    def _splitter(self, node: GraphNode, request: InferRequest
                  ) -> InferResponse:
        total = sum(s.weight for s in node.steps)
        pick = self._rng.uniform(0, total)
        acc = 0.0
        for step in node.steps:
            acc += step.weight
            if pick <= acc:
                return self._step(step, request)
        return self._step(node.steps[-1], request)


class TrafficSplitter:
    """Revision-level traffic split for canary rollout: routes a request to
    one of the revisions' backends per the InferenceService status traffic
    map (the ServingController maintains the map; this enforces it)."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def pick(self, traffic: dict[int, int]) -> int:
        if not traffic:
            raise ValueError("no traffic targets")
        total = sum(traffic.values())
        pick = self._rng.uniform(0, total)
        acc = 0.0
        for revision, weight in sorted(traffic.items()):
            acc += weight
            if pick <= acc:
                return revision
        return max(traffic)


def serve_repository(repository: ModelRepository) -> dict[str, Backend]:
    """Expose every model in a repository as router backends."""
    return {name: repository.get(name) for name in repository.names()}
